"""DAX disaggregation tests: directives, write-log durability,
snapshot+replay recovery, poller-driven rebalance (the
internal/clustertests pause-node shape for DAX)."""

import numpy as np
import pytest

from pilosa_tpu.dax import (
    Directive,
    Snapshotter,
    WriteLogger,
)
from pilosa_tpu.dax.server import DAXService

SHARD = 1 << 20

SCHEMA = {"indexes": [{"name": "t", "fields": [
    {"name": "f", "options": {"type": "set"}},
    {"name": "v", "options": {"type": "int", "min": 0, "max": 1000}},
]}]}


@pytest.fixture()
def dax(tmp_path):
    svc = DAXService(str(tmp_path), n_workers=3)
    yield svc
    svc.close()


def _seed(svc, n_shards=6):
    svc.queryer.apply_schema(SCHEMA)
    cols = [s * SHARD + 7 for s in range(n_shards)]
    svc.queryer.import_bits("t", "f", [1] * n_shards, cols)
    svc.queryer.import_values("t", "v", cols,
                              list(range(10, 10 * n_shards + 10, 10)))
    return cols


def test_writelogger_roundtrip(tmp_path):
    wl = WriteLogger(str(tmp_path / "wl"))
    v1 = wl.append("t", 0, {"op": "bits", "rows": [1], "cols": [2]})
    v2 = wl.append("t", 0, {"op": "bits", "rows": [1], "cols": [3]})
    assert (v1, v2) == (1, 2)
    assert len(wl.replay("t", 0)) == 2
    assert len(wl.replay("t", 0, from_version=1)) == 1
    wl.truncate_through("t", 0, 1)
    # versions are absolute: truncation drops entries but never
    # renumbers, so a snapshot taken at v1 stays aligned
    assert wl.version("t", 0) == 2
    assert len(wl.replay("t", 0, from_version=1)) == 1
    assert len(wl.replay("t", 0, from_version=2)) == 0
    assert wl.shards("t") == [0]


def test_snapshotter_versions(tmp_path):
    s = Snapshotter(str(tmp_path / "sn"))
    assert s.latest("t", 0) is None
    s.write("t", 0, 3, b"aaa")
    s.write("t", 0, 7, b"bbb")
    assert s.latest("t", 0) == (7, b"bbb")


def test_directive_assigns_shards(dax):
    _seed(dax)
    # all 6 shards are held, each by exactly one worker
    held = {}
    total = 0
    for w in dax.workers:
        for t, shards in w.held.items():
            held.setdefault(t, set()).update(shards)
            total += len(shards)
    assert held["t"] == set(range(6))
    assert total == 6  # disjoint ownership


def test_placement_balanced_and_stable():
    """Jump-hash job placement: roughly even over many shards, and
    adding a worker moves only ~1/n of the jobs (the balancer goal —
    no mass churn)."""
    from pilosa_tpu.dax.controller import _place
    addrs = ["w0", "w1", "w2"]
    before = {s: _place("t", s, addrs) for s in range(300)}
    counts = {a: 0 for a in addrs}
    for a in before.values():
        counts[a] += 1
    assert min(counts.values()) > 50  # ~100 each, statistically
    after = {s: _place("t", s, addrs + ["w3"])
             for s in range(300)}
    moved = [s for s in before if after[s] != before[s]]
    assert all(after[s] == "w3" for s in moved)  # only moves TO new
    assert len(moved) < 120  # ~1/4 expected


def test_dax_query_fan_out(dax):
    _seed(dax)
    r = dax.queryer.query("t", "Count(Row(f=1))")
    assert r["results"] == [6]
    r = dax.queryer.query("t", "Row(f=1)")
    assert r["results"][0]["columns"] == [s * SHARD + 7 for s in range(6)]
    r = dax.queryer.query("t", "Sum(Row(f=1), field=v)")
    assert r["results"][0] == {"value": sum(range(10, 70, 10)),
                               "count": 6}


def test_worker_death_recovery(dax):
    """Kill a worker; poller rebalances; data recovers from the
    write-log on the surviving workers."""
    _seed(dax)
    victim = dax.workers[0]
    dax.kill_worker(victim.address)
    dead = dax.controller.poll_once()
    assert victim.address in dead
    # all shards now held by survivors
    r = dax.queryer.query("t", "Count(Row(f=1))")
    assert r["results"] == [6]
    r = dax.queryer.query("t", "Sum(Row(f=1), field=v)")
    assert r["results"][0]["count"] == 6


def test_snapshot_plus_log_tail_recovery(dax):
    """Snapshot a shard, write more, then move the shard — the new
    owner must load snapshot + replay only the tail."""
    _seed(dax, n_shards=3)
    # find the worker holding shard 0 and snapshot it there
    addr, _ = dax.controller.worker_for("t", 0)
    owner = next(w for w in dax.workers if w.address == addr)
    owner.snapshot_shard("t", 0)
    ver = dax.wl.version("t", 0)
    assert dax.snaps.latest("t", 0)[0] == ver
    dax.wl.truncate_through("t", 0, ver)
    # more writes to shard 0 after the snapshot
    dax.queryer.import_bits("t", "f", [2], [5])
    # kill the owner; recovery = snapshot + tail replay elsewhere
    dax.kill_worker(addr)
    dax.controller.poll_once()
    assert dax.queryer.query("t", "Count(Row(f=1))")["results"] == [3]
    assert dax.queryer.query("t", "Count(Row(f=2))")["results"] == [1]


def test_stale_directive_ignored(dax):
    _seed(dax, n_shards=2)
    w = dax.workers[0]
    v = w.directive_version
    stale = Directive(address=w.address, version=v - 1,
                      assignments={"t": []})
    w.apply_directive(stale)  # no-op: version too old
    assert w.directive_version == v


def test_worker_rejects_unassigned_shard_write(dax):
    _seed(dax, n_shards=2)
    from pilosa_tpu.cluster.client import InternalClient, RemoteError
    # a shard assigned to a different worker
    addr, _ = dax.controller.worker_for("t", 0)
    other = next(w for w in dax.workers if w.address != addr)
    with pytest.raises(RemoteError) as e:
        InternalClient()._request(other.uri, "POST", "/dax/import", {
            "op": "bits", "table": "t", "shard": 0,
            "field": "f", "rows": [1], "cols": [1]})
    assert e.value.status == 409


def test_dax_sql_fronting(dax):
    """SQL over the compute fleet (queryer.go:134 QuerySQL): DDL ->
    controller schema; INSERT -> routed imports; SELECT compiles
    locally and executes on the workers."""
    q = dax.queryer
    r = q.sql("CREATE TABLE ev (_id id, code int min 0 max 1000, f id)")
    assert r["data"] == []
    assert "ev" in dax.controller.tables
    ins = ("INSERT INTO ev (_id, code, f) VALUES " +
           ", ".join(f"({s * SHARD + 1}, {s * 10}, 1)"
                     for s in range(5)))
    r = q.sql(ins)
    assert r["data"] == [[5]]
    # aggregates + WHERE pushdown execute remotely
    r = q.sql("SELECT count(*) FROM ev WHERE f = 1")
    assert r["data"] == [[5]]
    r = q.sql("SELECT sum(code) FROM ev")
    assert r["data"] == [[sum(s * 10 for s in range(5))]]
    r = q.sql("SELECT count(*) FROM ev WHERE code >= 20")
    assert r["data"] == [[3]]
    # row select with ORDER BY via remote Extract/Sort
    r = q.sql("SELECT _id, code FROM ev ORDER BY code DESC LIMIT 2")
    assert r["data"] == [[4 * SHARD + 1, 40], [3 * SHARD + 1, 30]]
    # DELETE ships remotely too
    q.sql("DELETE FROM ev WHERE code < 20")
    r = q.sql("SELECT count(*) FROM ev")
    assert r["data"] == [[3]]
    # clean unsupported error, not silent wrong answers
    import pytest as _pytest
    from pilosa_tpu.sql import SQLError
    with _pytest.raises(SQLError):
        q.sql("SELECT ev._id FROM ev JOIN ev2 ON ev.f = ev2._id")


def test_dax_sql_groupby_agg_and_replace(dax):
    """GROUP BY with SUM over the fleet carries agg_count on the wire;
    REPLACE INTO clears the record's old values first; DROP TABLE
    propagates to the controller (no resurrection on re-mirror)."""
    q = dax.queryer
    q.sql("CREATE TABLE g (_id id, r id, v int min 0 max 1000)")
    q.sql("INSERT INTO g (_id, r, v) VALUES (1, 1, 10), (2, 1, 20), "
          "(3, 2, 5)")
    r = q.sql("SELECT r, sum(v) FROM g GROUP BY r")
    assert sorted(r["data"]) == [[1, 30], [2, 5]]
    import pytest as _pytest  # noqa: F401
    from pilosa_tpu.sql import SQLError
    # BSI group-by takes the generic hashed path, served over the
    # fleet via bulk Extract column maps (r05; orchestrator.go shape)
    r = q.sql("SELECT v, count(*) FROM g GROUP BY v")
    assert sorted(r["data"]) == [[5, 1], [10, 1], [20, 1]]
    # REPLACE clears the old record
    q.sql("REPLACE INTO g (_id, r) VALUES (1, 2)")
    r = q.sql("SELECT count(*) FROM g WHERE r = 1")
    assert r["data"] == [[1]]
    r = q.sql("SELECT count(*) FROM g WHERE v IS NOT NULL")
    assert r["data"] == [[2]]  # record 1's v was cleared
    # DROP TABLE reaches the controller and stays dropped
    q.sql("DROP TABLE g")
    assert "g" not in dax.controller.tables
    with _pytest.raises(SQLError):
        q.sql("SELECT count(*) FROM g")
    q.sql("CREATE TABLE g (_id id, r id)")  # name is reusable


def test_dax_sql_order_by_timestamp_desc(dax):
    """DESC merge is type-agnostic (timestamps cross the wire as ISO
    strings, not numbers)."""
    q = dax.queryer
    q.sql("CREATE TABLE ts (_id id, t timestamp)")
    q.sql("INSERT INTO ts (_id, t) VALUES "
          f"(1, '2021-01-01T00:00'), ({SHARD + 2}, '2023-01-01T00:00'), "
          f"({2 * SHARD + 3}, '2022-01-01T00:00')")
    r = q.sql("SELECT _id FROM ts ORDER BY t DESC")
    assert [row[0] for row in r["data"]] == \
        [SHARD + 2, 2 * SHARD + 3, 1]


def test_dax_sql_bulk_insert_and_sort_offset(dax):
    """BULK INSERT routes to the workers (not the schema-only mirror);
    Sort with OFFSET hoists the offset to the cross-worker merge."""
    q = dax.queryer
    q.sql("CREATE TABLE b (_id id, v int min 0 max 10000)")
    rows = "\n".join(f"{s * SHARD + 1},{s * 10}" for s in range(6))
    r = q.sql(f"BULK INSERT INTO b (_id, v) FROM '{rows}' "
              "WITH FORMAT 'CSV' INPUT 'STREAM'")
    assert r["data"] == [[6]]
    # the data must live on the WORKERS: a fresh count is remote
    assert q.sql("SELECT count(*) FROM b")["data"] == [[6]]
    # Sort offset: each worker holds different shards; the offset
    # must apply once after the merge, not per worker
    r = q.query("b", "Sort(All(), field=v, offset=2, limit=3)")
    assert r["results"][0]["values"] == [20, 30, 40]
    r = q.sql("SELECT _id FROM b ORDER BY v LIMIT 2 OFFSET 1")
    assert [row[0] for row in r["data"]] == [SHARD + 1, 2 * SHARD + 1]


def test_directive_push_is_delta(dax):
    """Directives are content-diffed per worker (api_directive.go:172
    lifted to the push side): registering a shard owned by ONE worker
    must not re-push directives to the others."""
    _seed(dax, n_shards=4)
    before = {w.address: w.directive_version for w in dax.workers}
    # one new shard: exactly one worker's assignment changes
    addr, _ = dax.controller.worker_for("t", 17)
    dax.controller.add_shards("t", [17])
    changed = [w.address for w in dax.workers
               if w.directive_version != before[w.address]]
    assert changed == [addr], (changed, addr)


def test_rebalance_under_load_no_data_loss(dax):
    """3 workers, one killed MID-INGEST: the poller reassigns its
    shards and every ACKNOWLEDGED write survives (write-log + replay;
    poller/poller.go -> balancer -> api_directive.go:559 loadShard)."""
    import threading
    import time

    dax.queryer.apply_schema(SCHEMA)
    acked = []
    stop = threading.Event()
    errors = []

    def ingest():
        i = 0
        deadline = time.time() + 30
        # keep going past the stop signal until the load was REAL
        # (>= 60 acks): a wall-clock window alone under-ingests on a
        # contended box and fails the final load assertion flakily
        while i < 400 and time.time() < deadline and \
                (not stop.is_set() or len(acked) < 60):
            col = (i % 8) * SHARD + i  # spread over 8 shards
            try:
                dax.queryer.import_bits("t", "f", [1], [col])
                acked.append(col)
            except Exception:
                # unacknowledged mid-failover writes may be refused;
                # the ingester retries next round (idk semantics)
                time.sleep(0.01)
            i += 1
        stop.set()

    t = threading.Thread(target=ingest)
    t.start()
    time.sleep(0.15)  # mid-ingest
    victim = dax.workers[1]
    dax.kill_worker(victim.address)
    dax.controller.poll_once()
    time.sleep(0.3)  # keep ingesting AFTER the failover too
    stop.set()
    t.join(timeout=30)
    assert not t.is_alive()
    r = dax.queryer.query("t", "Row(f=1)")
    got = set(r["results"][0]["columns"])
    missing = [c for c in acked if c not in got]
    assert not missing, f"{len(missing)} acknowledged writes lost"
    assert len(acked) > 50  # the load was real


def test_dax_sql_shape_support_matrix(dax):
    """Which SQL shapes the DAX front end serves vs refuses (VERDICT
    r03 item 8, r04 matrix, r05 flips).  Served: filters, PQL
    aggregates, GROUP BY (including the generic hashed path over BSI
    columns), JOIN, DISTINCT, ORDER BY...LIMIT — the local-cell
    paths ride bulk Extract column maps over the compute fleet
    (dax/queryer/orchestrator.go:83,109 shape), and keyed fields /
    keyed tables translate at the front (ID-space workers)."""
    from pilosa_tpu.sql import SQLError

    dax.queryer.apply_schema({"indexes": [
        {"name": "s", "fields": [
            {"name": "g", "options": {"type": "mutex"}},
            {"name": "n", "options": {"type": "int", "min": 0,
                                      "max": 100}}]},
        {"name": "s2", "fields": [
            {"name": "m", "options": {"type": "int", "min": 0,
                                      "max": 100}}]},
    ]})
    dax.queryer.sql("INSERT INTO s (_id, g, n) VALUES "
                    "(1, 10, 5), (2, 20, 7), (3, 10, 9)")
    served = [
        ("SELECT count(*) FROM s", [[3]]),
        ("SELECT count(*) FROM s WHERE n > 5", [[2]]),
        ("SELECT sum(n) FROM s", [[21]]),
        ("SELECT g, count(*) FROM s GROUP BY g", [[10, 2], [20, 1]]),
        ("SELECT DISTINCT g FROM s", [[10], [20]]),
        ("SELECT _id FROM s WHERE g = 10 ORDER BY _id LIMIT 1",
         [[1]]),
    ]
    for q, want in served:
        got = dax.queryer.sql(q)["data"]
        assert sorted(map(repr, got)) == sorted(map(repr, want)), \
            (q, got)
    # r05: JOIN and the generic hashed GROUP BY are now SERVED via
    # bulk Extract column maps (the orchestrator's full-scan shape,
    # dax/queryer/orchestrator.go:83,109) — the r04 refusal rows flip
    dax.queryer.sql("INSERT INTO s2 (_id, m) VALUES (1, 5), (2, 9)")
    served2 = [
        ("SELECT s._id FROM s JOIN s2 ON s.n = s2.m",
         [[1], [3]]),
        ("SELECT n, count(*) FROM s GROUP BY n",
         [[5, 1], [7, 1], [9, 1]]),
    ]
    for q, want in served2:
        got = dax.queryer.sql(q)["data"]
        assert sorted(map(repr, got)) == sorted(map(repr, want)), \
            (q, got)
    # r05: nothing left in the matrix refuses — keyed FIELD rows and
    # keyed-_id TABLES both translate at the queryer (ID-space
    # workers, front-end translators)
    dax.queryer.sql("CREATE TABLE sk (_id id, k string); "
                    "INSERT INTO sk (_id, k) VALUES (1, 'x')")
    got = dax.queryer.sql("SELECT _id FROM sk WHERE k = 'x'")["data"]
    assert got == [[1]]
    dax.queryer.sql("CREATE TABLE sk2 (_id string, k int); "
                    "INSERT INTO sk2 (_id, k) VALUES ('a', 1)")
    got = dax.queryer.sql("SELECT _id FROM sk2 WHERE k = 1")["data"]
    assert got == [["a"]]


def test_controller_restart_loses_nothing(dax):
    """Durable controller (dax/controller/schemar + Transactor
    analog): kill the controller mid-workload — workers keep serving,
    a fresh controller reloads schema/workers/jobs/versions from the
    schemar DB, and its next rebalance is a DELTA (no re-push to
    unchanged workers)."""
    cols = _seed(dax)
    before = dax.queryer.query("t", "Row(f=1)")
    assert set(before["results"][0]["columns"]) == set(cols)

    old = dax.controller
    versions_before = {w.address: w.directive_version
                      for w in dax.workers}

    fresh = dax.restart_controller()
    assert fresh is not old
    # state reloaded: workers, schema tables, shard jobs
    assert sorted(fresh.workers) == sorted(old.workers)
    assert fresh.tables["t"] == set(range(6))
    assert [ix["name"] for ix in fresh.schema["indexes"]] == ["t"]

    # a no-op rebalance after restart is a delta: the reloaded
    # fingerprints skip every unchanged worker (no directive push, so
    # worker versions do not move)
    fresh.poll_once()
    assert {w.address: w.directive_version
            for w in dax.workers} == versions_before

    # the world still works end-to-end: reads, new shards, rebalance
    after = dax.queryer.query("t", "Row(f=1)")
    assert set(after["results"][0]["columns"]) == set(cols)
    new_col = 7 * SHARD + 3
    dax.queryer.import_bits("t", "f", [1], [new_col])
    got = dax.queryer.query("t", "Row(f=1)")
    assert new_col in set(got["results"][0]["columns"])
    # the new shard's owner took a new directive; the others did not
    moved = [w.address for w in dax.workers
             if w.directive_version != versions_before[w.address]]
    assert len(moved) == 1


def test_controller_restart_after_worker_death(dax):
    """Restart the controller, THEN kill a worker: the reloaded
    registry still drives failover correctly."""
    cols = _seed(dax)
    fresh = dax.restart_controller()
    victim = dax.workers[0]
    dax.kill_worker(victim.address)
    dead = fresh.poll_once()
    assert victim.address in dead
    r = dax.queryer.query("t", "Row(f=1)")
    assert set(r["results"][0]["columns"]) == set(cols)


def test_dax_bulk_insert_typechecks(dax):
    """The DAX BULK INSERT route runs the same MAP/TRANSFORM analysis
    as the local engine — a transform-count mismatch must error, not
    insert partial records."""
    from pilosa_tpu.sql import SQLError

    dax.queryer.apply_schema({"indexes": [
        {"name": "bt", "fields": [
            {"name": "a", "options": {"type": "int", "min": 0,
                                      "max": 100}}]}]})
    with pytest.raises(SQLError, match="mismatch in the count"):
        dax.queryer.sql(
            "BULK INSERT INTO bt (_id, a) map (0 ID, 1 INT) "
            "transform(@0) FROM x'1,5' "
            "with format 'CSV' input 'STREAM'")
    dax.queryer.sql(
        "BULK INSERT INTO bt (_id, a) map (0 ID, 1 INT) "
        "transform(@0, @1) FROM x'1,5\n2,7' "
        "with format 'CSV' input 'STREAM'")
    got = dax.queryer.sql("SELECT _id, a FROM bt")["data"]
    assert sorted(map(tuple, got)) == [(1, 5), (2, 7)]


def test_queryer_http_front(dax):
    """The dax single-binary surface: SQL + PQL + status over the
    queryer's HTTP front (dax/server/ analog; `pilosa-tpu dax`
    hosts this)."""
    import http.client
    import json as _json

    cols = _seed(dax)
    front = dax.serve_queryer()
    try:
        def req(method, path, body=None):
            c = http.client.HTTPConnection("127.0.0.1", front.port,
                                           timeout=30)
            c.request(method, path, body=body)
            out = _json.loads(c.getresponse().read())
            c.close()
            return out

        r = req("POST", "/sql", "SELECT count(*) FROM t")
        assert r["data"] == [[len(cols)]]
        r = req("POST", "/queryer/t",
                _json.dumps({"query": "Count(Row(f=1))"}))
        assert r["results"][0] == len(cols)
        st = req("GET", "/dax/status")
        assert len(st["workers"]) == 3
        assert st["tables"]["t"] == sorted(
            c // SHARD for c in cols)
    finally:
        front.close()


def test_queryer_front_json_sql_form(dax):
    """The front's /sql accepts both body forms of the standard
    endpoint: raw SQL text and {\"sql\": ...}; svc.close() tears the
    front down."""
    import http.client
    import json as _json

    _seed(dax, n_shards=2)
    front = dax.serve_queryer()
    c = http.client.HTTPConnection("127.0.0.1", front.port,
                                   timeout=30)
    c.request("POST", "/sql",
              body=_json.dumps({"sql": "SELECT count(*) FROM t"}))
    out = _json.loads(c.getresponse().read())
    c.close()
    assert out["data"] == [[2]]


def test_dax_runs_reference_sql_corpus_sample(dax):
    """A sample of the PORTED reference SQL corpus runs over the DAX
    fleet with the same expectations as the local engine — HAVING,
    BETWEEN, DISTINCT, ORDER BY, GROUP BY, and the joinTests family
    (the r05 served shapes), end to end through the queryer."""
    from pilosa_tpu.sql import SQLError

    from tests.sql_defs_ref import FAMILIES
    from tests.test_sql_ref_conformance import canon, conv_exp

    pick = {"defs_having.go:selectHavingTests",
            "defs_between.go:betweenTests",
            "defs_between.go:notBetweenTests",
            "defs_distinct.go:distinctTests",
            "defs_orderby.go:orderByTests",
            "defs_groupby.go:groupByTests",
            "defs_join.go:joinTestsUsers",
            "defs_join.go:joinTestsOrders",
            "defs_join.go:joinTestsQuantity",
            "defs_join.go:joinTests"}
    fam = [(o, s, c) for o, s, c in FAMILIES if o in pick]
    assert len(fam) == len(pick)
    q = dax.queryer
    ran = 0
    # corpus order: sibling table families precede their consumers
    for origin, setup, cases in fam:
        for s in setup or []:
            q.sql(s)
        for cname, sql, exp in cases:
            if isinstance(exp, tuple) and exp and exp[0] == "error":
                with pytest.raises(SQLError) as exc:
                    q.sql(sql)
                assert exp[1].lower() in str(exc.value).lower(), \
                    (origin, cname)
                ran += 1
                continue
            got = [tuple(r) for r in q.sql(sql)["data"]]
            expc = [tuple(conv_exp(c) for c in r) for r in exp]
            if expc and got and all(len(r) < len(got[0])
                                    for r in expc):
                w = max(len(r) for r in expc)
                got = [r[:w] for r in got]
                expc = [r[:w] for r in expc]
            assert canon(got) == canon(expc), (origin, cname, got,
                                               expc)
            ran += 1
    assert ran >= 60


def test_keyed_translation_survives_service_restart(tmp_path):
    """Front-end key translators persist under the storage dir: a
    fresh DAXService over the same dir (new queryer, new workers
    recovering from snapshot+write-log) still resolves existing keys
    to the same ids."""
    svc = DAXService(str(tmp_path), n_workers=2)
    q = svc.queryer
    q.sql("CREATE TABLE sk (_id id, k string)")
    q.sql("INSERT INTO sk (_id, k) VALUES (1, 'x'), (2, 'y')")
    assert q.sql("SELECT _id FROM sk WHERE k = 'y'")["data"] == [[2]]
    svc.close()

    svc2 = DAXService(str(tmp_path), n_workers=2)
    try:
        q2 = svc2.queryer
        assert q2.sql(
            "SELECT _id FROM sk WHERE k = 'y'")["data"] == [[2]]
        # new keys keep minting AFTER the reloaded ones
        q2.sql("INSERT INTO sk (_id, k) VALUES (3, 'z')")
        got = q2.sql("SELECT _id, k FROM sk")["data"]
        assert sorted(map(tuple, got)) == [(1, "x"), (2, "y"),
                                           (3, "z")]
    finally:
        svc2.close()


def test_dax_keyed_table_end_to_end(dax):
    """Keyed-_id tables over the fleet: column keys mint at the
    front, workers run in ID space, and results carry the keys back
    (the defs_keyed shapes)."""
    q = dax.queryer
    q.sql("CREATE TABLE kt (_id string, an_int int min 0 max 100, "
          "a_string string)")
    q.sql("INSERT INTO kt (_id, an_int, a_string) VALUES "
          "('one', 11, 'str1'), ('two', 22, 'str2'), "
          "('three', 33, 'str3')")
    got = q.sql("SELECT _id, an_int, a_string FROM kt")["data"]
    assert sorted(map(tuple, got)) == [
        ("one", 11, "str1"), ("three", 33, "str3"),
        ("two", 22, "str2")]
    assert q.sql("SELECT _id FROM kt WHERE an_int = 22")["data"] == \
        [["two"]]
    assert q.sql(
        "SELECT _id FROM kt WHERE a_string = 'str3'")["data"] == \
        [["three"]]
    assert q.sql("SELECT count(*) FROM kt")["data"] == [[3]]


def test_dax_sql_bool_explicit_null_clears(dax):
    """defs_bool select-all2 over the DAX front (ADVICE r05): an
    explicit NULL in an INSERT tuple ships a clear for that (field,
    column) to the owning worker — matching apply_record — instead of
    being silently skipped, and NULL-only records still insert."""
    q = dax.queryer
    q.sql("CREATE TABLE singleboolfield (_id id, a_bool bool)")
    q.sql("insert into singleboolfield (_id, a_bool) values "
          "(1, true), (2, true), (3, false), (4, false), "
          "(5, null), (6, null)")
    out = q.sql("select * from singleboolfield")
    assert out["data"] == [[1, True], [2, True], [3, False],
                           [4, False], [5, None], [6, None]]
    q.sql("insert into singleboolfield (_id, a_bool) values "
          "(1, false), (2, null), (3, true), (4, null), "
          "(5, false), (6, true)")
    out = q.sql("select * from singleboolfield")
    assert out["data"] == [[1, False], [2, None], [3, True],
                           [4, None], [5, False], [6, True]]


def test_dax_raw_pql_keyed_translation(dax):
    """Raw keyed-shape PQL through Queryer.query routes via the
    translate_call/translate_result pair (ADVICE r05): string row
    values become ids before the ID-space fan-out, and result ids
    come back with keys attached — it must not silently match
    nothing."""
    q = dax.queryer
    q.sql("CREATE TABLE kt (_id id, tag stringset)")
    q.sql("INSERT INTO kt (_id, tag) VALUES (1, ('a','b')), "
          "(2, ('b'))")
    assert q.query("kt", "Count(Row(tag='b'))")["results"] == [2]
    assert q.query("kt", "Count(Row(tag='a'))")["results"] == [1]
    # unknown key matches nothing (FindKeys semantics), not an error
    assert q.query("kt", "Count(Row(tag='zzz'))")["results"] == [0]
    pairs = q.query("kt", "TopN(tag, n=10)")["results"][0]
    assert [(p["key"], p["count"]) for p in pairs] == \
        [("b", 2), ("a", 1)]
    # Rows on a keyed field returns keys (single-node parity)
    assert q.query("kt", "Rows(tag)")["results"][0] == ["a", "b"]


def test_dax_clear_op_replay_recovery(dax):
    """The new "clear" write-log op replays like any write: kill the
    owning worker after an explicit-NULL clear; the rebuilt worker
    must come back with the clear applied, not the stale value."""
    q = dax.queryer
    q.sql("CREATE TABLE rb (_id id, b bool)")
    q.sql("INSERT INTO rb (_id, b) VALUES (1, true)")
    q.sql("INSERT INTO rb (_id, b) VALUES (1, null)")
    owner_addr, _ = dax.controller.worker_for("rb", 0)
    dax.kill_worker(owner_addr)
    dax.controller.poll_once()
    out = q.sql("select * from rb")
    assert out["data"] == [[1, None]]
