"""Data model tests: fragments, fields, holder schema persistence."""

import numpy as np
import pytest

from pilosa_tpu.models import FieldOptions, FieldType, Holder, TimeQuantum
from pilosa_tpu.models.fragment import Fragment
from pilosa_tpu.ops import bsi as bsi_ops

W = 1 << 12


def test_fragment_set_clear_contains():
    f = Fragment("i", "f", "standard", 0, width=W)
    assert f.set_bit(3, 100) is True
    assert f.set_bit(3, 100) is False
    assert f.contains(3, 100)
    assert f.clear_bit(3, 100) is True
    assert f.clear_bit(3, 100) is False
    assert not f.contains(3, 100)


def test_fragment_bulk_import():
    f = Fragment("i", "f", "standard", 0, width=W)
    rows = [1, 1, 2, 2, 2]
    cols = [10, 20, 10, 30, 40]
    f.import_bits(rows, cols)
    assert f.row_count(1) == 2 and f.row_count(2) == 3
    f.import_bits([1], [10], clear=True)
    assert f.row_count(1) == 1


class TestSparseRows:
    """Hybrid sparse/dense row store (the in-memory analog of the
    array/bitmap container split, roaring/container_stash.go:46-85):
    cold sparse rows stay as column arrays, hot rows promote to packed
    words, and every read/write path agrees across the threshold."""

    def test_sparse_until_threshold(self):
        from pilosa_tpu.models.fragment import SPARSE_MAX
        from pilosa_tpu.shardwidth import SHARD_WIDTH
        f = Fragment("i", "f", "standard", 0, width=SHARD_WIDTH)
        f.set_bit(7, 10)
        f.set_bit(7, 3)
        assert f.sparse_row_count == 1
        assert f.contains(7, 3) and f.contains(7, 10)
        assert not f.contains(7, 4)
        assert f.row_count(7) == 2
        assert np.asarray(f.row_words(7)).sum() > 0
        # crossing the threshold promotes to dense, same semantics
        cols = np.arange(SPARSE_MAX + 5) * 17 % SHARD_WIDTH
        f.import_bits(np.full(cols.size, 9), cols)
        assert f.sparse_row_count == 1  # row 9 went dense
        assert f.row_count(9) == np.unique(cols).size

    def test_million_sparse_rows_bounded_memory(self):
        """1M rows x 2 bits at full shard width stays in tens of MB —
        dense would need ~128 GiB (VERDICT r02 item 2)."""
        from pilosa_tpu.shardwidth import SHARD_WIDTH
        f = Fragment("i", "f", "standard", 0, width=SHARD_WIDTH)
        n = 1_000_000
        rows = np.repeat(np.arange(n // 2), 2)
        cols = (rows * 2654435761) % SHARD_WIDTH
        cols[1::2] = (cols[1::2] + 7) % SHARD_WIDTH
        f.import_bits(rows, cols)
        assert f.sparse_row_count == n // 2
        assert f.memory_bytes() < 200 * (1 << 20)
        r = int(rows[123456])
        assert f.row_count(r) in (1, 2)  # 2 unless the cols collided

    def test_clear_and_delete_on_sparse(self):
        f = Fragment("i", "f", "standard", 0, width=W)
        f.import_bits([1, 1, 2], [5, 9, 5])
        assert f.clear_bit(1, 5)
        assert f.row_count(1) == 1
        mask = np.zeros(W // 32, dtype=np.uint32)
        mask[0] = np.uint32(1) << 5  # column 5
        assert f.clear_columns(mask) is True
        assert f.row_count(2) == 0
        assert f.row_ids == [1]

    def test_set_row_words_recompresses(self):
        f = Fragment("i", "f", "standard", 0, width=W)
        words = np.zeros(W // 32, dtype=np.uint32)
        words[3] = 0b1011
        f.set_row_words(4, words)
        assert f.sparse_row_count == 1
        assert f.row_count(4) == 3


def test_fragment_set_value_roundtrip():
    f = Fragment("i", "v", "bsig_v", 0, width=W)
    f.set_value(5, 8, 100)
    f.set_value(6, 8, -42)
    planes = np.asarray(f.device_planes(8))
    cols, vals = bsi_ops.decode(planes)
    assert dict(zip(cols.tolist(), vals)) == {5: 100, 6: -42}
    # overwrite
    f.set_value(5, 8, 7)
    cols, vals = bsi_ops.decode(np.asarray(f.device_planes(8)))
    assert dict(zip(cols.tolist(), vals)) == {5: 7, 6: -42}


def test_fragment_import_values_last_write_wins():
    f = Fragment("i", "v", "bsig_v", 0, width=W)
    f.import_values([1, 2, 1], [5, 6, 9], depth=8)
    cols, vals = bsi_ops.decode(np.asarray(f.device_planes(8)))
    assert dict(zip(cols.tolist(), vals)) == {1: 9, 2: 6}
    f.import_values([2], [0], depth=8, clear=True)
    cols, vals = bsi_ops.decode(np.asarray(f.device_planes(8)))
    assert dict(zip(cols.tolist(), vals)) == {1: 9}


def test_field_depth_growth():
    h = Holder(width=W)
    idx = h.create_index("i")
    f = idx.create_field("v", FieldOptions(type=FieldType.INT))
    f.set_value(1, 3)
    assert f.bit_depth == 2
    f.set_value(2, 1000)  # grows depth
    assert f.bit_depth == 10
    # older value still readable at new depth
    frag = f.views[f.bsi_view].fragment(0)
    cols, vals = bsi_ops.decode(np.asarray(frag.device_planes(f.bit_depth)))
    assert dict(zip(cols.tolist(), vals)) == {1: 3, 2: 1000}


def test_field_min_max_option_depth():
    h = Holder(width=W)
    idx = h.create_index("i")
    f = idx.create_field("v", FieldOptions(type=FieldType.INT,
                                           min=-1000, max=1000))
    assert f.bit_depth == 10


def test_holder_schema_roundtrip(tmp_path):
    h = Holder(path=str(tmp_path), width=W)
    idx = h.create_index("i", keys=False)
    idx.create_field("s")
    idx.create_field("v", FieldOptions(type=FieldType.INT, min=0, max=100))
    idx.create_field("d", FieldOptions(type=FieldType.DECIMAL, scale=3))
    idx.create_field("t", FieldOptions(type=FieldType.TIME,
                                       time_quantum=TimeQuantum("YMD")))
    h.save_schema()

    h2 = Holder(path=str(tmp_path), width=W)
    h2.load_schema()
    idx2 = h2.index("i")
    assert idx2 is not None
    assert sorted(f.name for f in idx2.public_fields()) == ["d", "s", "t", "v"]
    assert idx2.field("v").options.type == FieldType.INT
    assert idx2.field("d").options.scale == 3
    assert idx2.field("t").options.time_quantum == "YMD"


def test_index_duplicate_field_raises():
    h = Holder(width=W)
    idx = h.create_index("i")
    idx.create_field("f")
    with pytest.raises(ValueError):
        idx.create_field("f")
    idx.create_field("f", ok_if_exists=True)


def test_timestamp_ns_exact():
    import datetime as dt
    opts = FieldOptions(type=FieldType.TIMESTAMP, time_unit="ns")
    t = dt.datetime(2024, 1, 1, 0, 0, 0, 1, tzinfo=dt.timezone.utc)
    assert opts.timestamp_to_int(t) == (
        (t - opts.epoch).days * 86400 + (t - opts.epoch).seconds
    ) * 10**9 + 1000
