"""Data model tests: fragments, fields, holder schema persistence."""

import numpy as np
import pytest

from pilosa_tpu.models import FieldOptions, FieldType, Holder, TimeQuantum
from pilosa_tpu.models.fragment import Fragment
from pilosa_tpu.ops import bsi as bsi_ops

W = 1 << 12


def test_fragment_set_clear_contains():
    f = Fragment("i", "f", "standard", 0, width=W)
    assert f.set_bit(3, 100) is True
    assert f.set_bit(3, 100) is False
    assert f.contains(3, 100)
    assert f.clear_bit(3, 100) is True
    assert f.clear_bit(3, 100) is False
    assert not f.contains(3, 100)


def test_fragment_bulk_import():
    f = Fragment("i", "f", "standard", 0, width=W)
    rows = [1, 1, 2, 2, 2]
    cols = [10, 20, 10, 30, 40]
    f.import_bits(rows, cols)
    assert f.row_count(1) == 2 and f.row_count(2) == 3
    f.import_bits([1], [10], clear=True)
    assert f.row_count(1) == 1


def test_fragment_set_value_roundtrip():
    f = Fragment("i", "v", "bsig_v", 0, width=W)
    f.set_value(5, 8, 100)
    f.set_value(6, 8, -42)
    planes = np.asarray(f.device_planes(8))
    cols, vals = bsi_ops.decode(planes)
    assert dict(zip(cols.tolist(), vals)) == {5: 100, 6: -42}
    # overwrite
    f.set_value(5, 8, 7)
    cols, vals = bsi_ops.decode(np.asarray(f.device_planes(8)))
    assert dict(zip(cols.tolist(), vals)) == {5: 7, 6: -42}


def test_fragment_import_values_last_write_wins():
    f = Fragment("i", "v", "bsig_v", 0, width=W)
    f.import_values([1, 2, 1], [5, 6, 9], depth=8)
    cols, vals = bsi_ops.decode(np.asarray(f.device_planes(8)))
    assert dict(zip(cols.tolist(), vals)) == {1: 9, 2: 6}
    f.import_values([2], [0], depth=8, clear=True)
    cols, vals = bsi_ops.decode(np.asarray(f.device_planes(8)))
    assert dict(zip(cols.tolist(), vals)) == {1: 9}


def test_field_depth_growth():
    h = Holder(width=W)
    idx = h.create_index("i")
    f = idx.create_field("v", FieldOptions(type=FieldType.INT))
    f.set_value(1, 3)
    assert f.bit_depth == 2
    f.set_value(2, 1000)  # grows depth
    assert f.bit_depth == 10
    # older value still readable at new depth
    frag = f.views[f.bsi_view].fragment(0)
    cols, vals = bsi_ops.decode(np.asarray(frag.device_planes(f.bit_depth)))
    assert dict(zip(cols.tolist(), vals)) == {1: 3, 2: 1000}


def test_field_min_max_option_depth():
    h = Holder(width=W)
    idx = h.create_index("i")
    f = idx.create_field("v", FieldOptions(type=FieldType.INT,
                                           min=-1000, max=1000))
    assert f.bit_depth == 10


def test_holder_schema_roundtrip(tmp_path):
    h = Holder(path=str(tmp_path), width=W)
    idx = h.create_index("i", keys=False)
    idx.create_field("s")
    idx.create_field("v", FieldOptions(type=FieldType.INT, min=0, max=100))
    idx.create_field("d", FieldOptions(type=FieldType.DECIMAL, scale=3))
    idx.create_field("t", FieldOptions(type=FieldType.TIME,
                                       time_quantum=TimeQuantum("YMD")))
    h.save_schema()

    h2 = Holder(path=str(tmp_path), width=W)
    h2.load_schema()
    idx2 = h2.index("i")
    assert idx2 is not None
    assert sorted(f.name for f in idx2.public_fields()) == ["d", "s", "t", "v"]
    assert idx2.field("v").options.type == FieldType.INT
    assert idx2.field("d").options.scale == 3
    assert idx2.field("t").options.time_quantum == "YMD"


def test_index_duplicate_field_raises():
    h = Holder(width=W)
    idx = h.create_index("i")
    idx.create_field("f")
    with pytest.raises(ValueError):
        idx.create_field("f")
    idx.create_field("f", ok_if_exists=True)


def test_timestamp_ns_exact():
    import datetime as dt
    opts = FieldOptions(type=FieldType.TIMESTAMP, time_unit="ns")
    t = dt.datetime(2024, 1, 1, 0, 0, 0, 1, tzinfo=dt.timezone.utc)
    assert opts.timestamp_to_int(t) == (
        (t - opts.epoch).days * 86400 + (t - opts.epoch).seconds
    ) * 10**9 + 1000
