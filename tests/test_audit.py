"""Continuous correctness auditing (obs/audit.py): production
shadow-execution verifier, replica scrubber, maintained-result drift
audits.

The contract under test: the audit plane NEVER false-positives (a
write racing a sampled serve skips-and-counts, it does not fire), the
``audit-corrupt`` drill is ALWAYS caught by every verifier kind
(shadow / cache / standing / replica), the kill switch restores
bit-exact untouched serving, a saturated audit queue sheds audits —
never queries — and a hand-diverged replica block is detected (counted
as a mismatch, incident fired) and then repaired through the existing
resync path.
"""

import json

import numpy as np
import pytest

from pilosa_tpu.executor.executor import Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.models.schema import FieldOptions, FieldType
from pilosa_tpu.obs import audit, faults, incidents


@pytest.fixture(autouse=True)
def _reset_audit():
    audit.configure(enabled=True, sample_rate=0.01, route_rates={},
                    queue_max=64, concurrency=1, scrub_cache_n=4,
                    scrub_standing_n=2, scrub_replica_n=2,
                    quarantine=32)
    faults.clear()
    yield
    faults.clear()
    audit.configure(enabled=True, sample_rate=0.01, route_rates={})


@pytest.fixture()
def fresh_incidents(tmp_path):
    m = incidents.IncidentManager(dir=str(tmp_path / "inc"),
                                  min_interval_s=3600.0)
    prev = incidents.swap(m)
    yield m
    incidents.swap(prev)


def build(n=200):
    h = Holder(width=1 << 12)
    idx = h.create_index("i")
    idx.create_field("a", FieldOptions(type=FieldType.SET,
                                       cache_type="none"))
    idx.create_field("b")
    ex = Executor(h)
    for c in range(n):
        ex.execute("i", f"Set({c}, a={c % 4})")
        ex.execute("i", f"Set({c}, b={c % 6})")
    srv = ex.enable_serving(window_s=0.0, max_batch=8)
    return h, ex, srv


def outcome(srv, kind, oc):
    return srv.audit.counts.get((kind, oc), 0)


# ---------------------------------------------------------------------------
# no false positives
# ---------------------------------------------------------------------------

def test_no_false_positives_under_write_storm():
    """Seeded property run: sample EVERY serve (rate 1.0) while
    writes interleave with reads.  Matches and stale_skips are the
    only legal shadow outcomes — one mismatch is a plane bug."""
    audit.configure(sample_rate=1.0)
    h, ex, srv = build(n=160)
    srv.audit.seed(0xF00D)
    rng = np.random.default_rng(0xF00D)
    qs = ["Count(Row(a=1))", "Row(a=2)", "TopN(a, n=3)",
          "Count(Union(Row(a=0), Row(b=5)))",
          "GroupBy(Rows(a), Rows(b))"]
    for step in range(60):
        col = int(rng.integers(0, 500))
        fld = "a" if rng.integers(0, 2) else "b"
        rid = int(rng.integers(0, 4 if fld == "a" else 6))
        op = "Clear" if rng.integers(0, 3) == 0 else "Set"
        ex.execute_serving("i", f"{op}({col}, {fld}={rid})")
        ex.execute_serving("i", qs[step % len(qs)])
    assert srv.audit.wait_idle(30)
    assert outcome(srv, "shadow", "mismatch") == 0, \
        srv.audit.describe()
    assert outcome(srv, "shadow", "match") > 0
    assert not srv.audit.quarantine


def test_scrubbers_no_false_positives():
    """Cache + standing scrub passes over a live (quiesced) system
    must come back all-match."""
    audit.configure(sample_rate=1.0, scrub_cache_n=8,
                    scrub_standing_n=8)
    h, ex, srv = build(n=120)
    srv.standing.register("i", "Count(Row(a=1))")
    srv.standing.register("i", "TopN(a, n=2)")
    for q in ["Count(Row(a=0))", "Row(a=3)", "Count(Row(b=2))"]:
        ex.execute_serving("i", q)
        ex.execute_serving("i", q)  # second serve hits the cache
    assert srv.audit.wait_idle(30)
    srv.audit.scrub()
    assert srv.audit.wait_idle(30)
    d = srv.audit.describe()
    assert outcome(srv, "cache", "mismatch") == 0, d
    assert outcome(srv, "standing", "mismatch") == 0, d
    assert outcome(srv, "cache", "match") > 0, d
    assert outcome(srv, "standing", "match") > 0, d
    assert d["scrub"]["cache_scanned"] > 0
    assert d["scrub"]["standing_scanned"] == 2


# ---------------------------------------------------------------------------
# the audit-corrupt drill: every verifier kind must catch it
# ---------------------------------------------------------------------------

def test_corruption_drill_serve_seam(fresh_incidents):
    """A bit flipped in a SERVED result (the answer the client saw)
    is caught by the shadow verifier: exactly one mismatch, exactly
    one incident bundle carrying both digests and both arms."""
    audit.configure(sample_rate=1.0)
    h, ex, srv = build()
    q = "Count(Row(a=1))"
    clean = Executor(h).execute("i", q)
    faults.inject("audit-corrupt", match="serve:", times=1)
    served = ex.execute_serving("i", q)
    assert served != clean  # the drill corrupted what was served
    assert srv.audit.wait_idle(30)
    assert outcome(srv, "shadow", "mismatch") == 1
    (ent,) = srv.audit.quarantine
    assert ent["kind"] == "shadow"
    assert ent["live_digest"] != ent["shadow_digest"]
    assert ent["shadow_arm"]["arm"] == "host-loop"
    assert ent["shadow_arm"]["use_stacked"] is False
    assert ent["live_arm"]["route"] in ("solo", "fused", "cached")
    # exactly ONE bundle (min_interval_s dedups any repeat)
    assert fresh_incidents.wait_idle(10)
    bundles = [b for b in fresh_incidents.list()
               if b["trigger"] == "audit-mismatch"]
    assert len(bundles) == 1
    ctx = fresh_incidents.fetch(bundles[0]["id"])["context"]
    assert ctx["live_digest"] == ent["live_digest"]
    assert ctx["shadow_digest"] == ent["shadow_digest"]
    # the drill was one-shot: the next serve is clean and matches
    assert ex.execute_serving("i", q) == clean
    assert srv.audit.wait_idle(30)
    assert outcome(srv, "shadow", "mismatch") == 1


def test_corruption_drill_cache_seam():
    """A bit flipped in a STORED ResultCache entry (the serve in
    flight stays clean) is caught by the cache scrubber."""
    audit.configure(sample_rate=1.0)
    h, ex, srv = build()
    q = "Count(Row(a=0))"
    clean = Executor(h).execute("i", q)
    # first serve: stores clean, notes the key in the side-table
    assert ex.execute_serving("i", q) == clean
    assert srv.audit.wait_idle(30)
    # invalidate, arm, re-serve: the re-store corrupts the ENTRY only
    ex.execute_serving("i", "Set(9001, a=3)")
    faults.inject("audit-corrupt", match="cache:", times=1)
    assert ex.execute_serving("i", q) == clean
    assert srv.audit.wait_idle(30)
    assert outcome(srv, "shadow", "mismatch") == 0  # serve was clean
    srv.audit.scrub()
    assert srv.audit.wait_idle(30)
    assert outcome(srv, "cache", "mismatch") == 1, \
        srv.audit.describe()
    ents = [e for e in srv.audit.quarantine if e["kind"] == "cache"]
    assert len(ents) == 1
    assert ents[0]["live_digest"] != ents[0]["shadow_digest"]


def test_corruption_drill_standing_seam():
    """A bit flipped in a MAINTAINED standing result is caught by the
    drift audit at the next scrub quiesce point."""
    audit.configure(sample_rate=0.0)  # scrub-only detection
    h, ex, srv = build()
    q = "Count(Row(a=1))"
    srv.standing.register("i", q)
    faults.inject("audit-corrupt", match="standing:", times=1)
    ex.execute_serving("i", "Set(9002, a=1)")
    ex.execute_serving("i", q)  # maintenance runs; drill corrupts
    srv.audit.scrub()
    assert srv.audit.wait_idle(30)
    assert outcome(srv, "standing", "mismatch") == 1, \
        srv.audit.describe()
    ents = [e for e in srv.audit.quarantine
            if e["kind"] == "standing"]
    assert len(ents) == 1
    assert ents[0]["live_digest"] != ents[0]["shadow_digest"]


# ---------------------------------------------------------------------------
# kill switch
# ---------------------------------------------------------------------------

def test_kill_switch_bit_exact(monkeypatch):
    """PILOSA_TPU_AUDIT=0 disables the whole plane at runtime and the
    A/B serve stream stays bit-exact against cold execution."""
    audit.configure(sample_rate=1.0)
    h, ex, srv = build(n=120)
    cold = Executor(h)
    qs = ["Count(Row(a=1))", "Row(a=2)", "TopN(a, n=3)"]
    on = [ex.execute_serving("i", q) for q in qs]
    assert srv.audit.wait_idle(30)
    sampled_before = outcome(srv, "shadow", "sampled")
    assert sampled_before == len(qs)
    monkeypatch.setenv("PILOSA_TPU_AUDIT", "0")
    assert not audit.enabled()
    off = [ex.execute_serving("i", q) for q in qs]
    srv.audit.scrub()  # scrub gate no-ops too
    assert on == off == [cold.execute("i", q) for q in qs]
    assert outcome(srv, "shadow", "sampled") == sampled_before
    assert srv.audit.scrub_stats["ticks"] == 0
    monkeypatch.delenv("PILOSA_TPU_AUDIT")
    ex.execute_serving("i", qs[0])
    assert srv.audit.wait_idle(30)
    assert outcome(srv, "shadow", "sampled") == sampled_before + 1


def test_env_twin_and_route_rates(monkeypatch):
    """[audit] config knobs flow through apply_audit_settings, env
    twins win, and route-rate overrides beat the global rate."""
    from pilosa_tpu import config as cfg
    monkeypatch.setenv("PILOSA_TPU_AUDIT_SAMPLE_RATE", "0.5")
    monkeypatch.setenv("PILOSA_TPU_AUDIT_ROUTE_RATES",
                       "cached=1.0,fused=0")
    c = cfg.load()
    assert c.audit_sample_rate == 0.5
    assert audit.parse_route_rates(c.audit_route_rates) == \
        {"cached": 1.0, "fused": 0.0}
    c.apply_audit_settings()
    try:
        assert audit._SAMPLE_RATE == 0.5
        assert audit._ROUTE_RATES == {"cached": 1.0, "fused": 0.0}
    finally:
        audit.configure(sample_rate=0.01, route_rates={})
    # malformed operator input is ignored, never raises
    assert audit.parse_route_rates("garbage,=3,x=notafloat") == {}


# ---------------------------------------------------------------------------
# scheduler-class isolation
# ---------------------------------------------------------------------------

def test_saturated_audit_plane_sheds_audits_not_queries():
    """Audit slots busy + queue full: every audit sheds (counted),
    every query still answers bit-exact.  Audits can never steal
    serving capacity."""
    audit.configure(sample_rate=1.0, queue_max=1)
    h, ex, srv = build(n=120)
    cold = Executor(h)
    slot = srv.sched.audit_slot()  # hold the ONLY audit slot
    assert slot is not None
    assert srv.sched.audit_slot() is None  # cap enforced
    try:
        for i in range(10):
            q = f"Count(Row(a={i % 4}))"
            assert ex.execute_serving("i", q) == cold.execute("i", q)
        # give queued samples time to reach the busy-cap check
        import time
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and srv.audit.queue_depth():
            time.sleep(0.01)
    finally:
        slot.release()
    assert srv.audit.wait_idle(30)
    d = srv.audit.describe()
    assert outcome(srv, "shadow", "match") == 0, d
    assert outcome(srv, "shadow", "mismatch") == 0, d
    assert outcome(srv, "shadow", "shed") == 10, d
    # released: the plane verifies again
    ex.execute_serving("i", "Count(Row(a=1))")
    assert srv.audit.wait_idle(30)
    assert outcome(srv, "shadow", "match") == 1


# ---------------------------------------------------------------------------
# flight-record integration
# ---------------------------------------------------------------------------

def test_flight_records_carry_audit_outcome():
    from pilosa_tpu.obs import flight
    from pilosa_tpu.server.http import filter_flight_records
    audit.configure(sample_rate=0.0)
    h, ex, srv = build()
    ex.execute_serving("i", "Count(Row(a=2))")  # never sampled
    audit.configure(sample_rate=1.0)
    ex.execute_serving("i", "Count(Row(a=1))")
    assert srv.audit.wait_idle(30)
    recs = flight.recorder.recent(50)
    hits = filter_flight_records(recs, audited="1")
    assert hits and all(r["audited"] for r in hits)
    assert any(r.get("audit_outcome") == "match" for r in hits)
    misses = filter_flight_records(recs, audited="0")
    assert all(not r.get("audited") for r in misses)
    assert len(hits) + len(misses) == len(recs)


# ---------------------------------------------------------------------------
# replica anti-entropy scrub (cluster)
# ---------------------------------------------------------------------------

def test_replica_scrub_detects_and_repairs(fresh_incidents):
    """A hand-diverged fragment block on one replica is DETECTED
    (mismatch counted, quarantine entry, incident bundle) and then
    repaired through the existing block-pull path — checksums agree
    again afterwards."""
    from pilosa_tpu.cluster import ClusterNode, InMemDisCo
    disco = InMemDisCo(lease_ttl=30)
    nodes = [ClusterNode(f"n{i}", disco, holder=Holder(),
                         replica_n=2, heartbeat_interval=30).open()
             for i in range(2)]
    try:
        n0, n1 = nodes
        n0.apply_schema({"indexes": [{"name": "c", "fields": [
            {"name": "f", "options": {"type": "set"}}]}]})
        cols = list(range(64))
        n0.import_bits("c", "f", [1] * len(cols), cols)
        assert n0.query("c", "Count(Row(f=1))")["results"] == [64]
        # hand-diverge n0's local copy, bypassing replication
        n0.api.holder.index("c").field("f").set_bit(1, 1000)
        before = n0.api.fragment_checksums("c", "f", "standard", 0)
        assert before != n1.api.fragment_checksums(
            "c", "f", "standard", 0)
        scanned = n0.audit_scrub(budget=16)
        assert scanned > 0
        ents = [e for e in n0.api.executor.serving.audit.quarantine
                if e["kind"] == "replica"]
        assert len(ents) == 1
        assert ents[0]["fragment"] == "c/f/0"
        assert ents[0]["diverged"]
        assert ents[0]["repaired_blocks"] > 0
        # repaired: local checksums converge back to the peer's
        assert n0.api.fragment_checksums("c", "f", "standard", 0) \
            == n1.api.fragment_checksums("c", "f", "standard", 0)
        assert n0.query("c", "Count(Row(f=1))")["results"] == [64]
        assert fresh_incidents.wait_idle(10)
        assert any(b["trigger"] == "audit-mismatch"
                   for b in fresh_incidents.list())
        # a second pass over the healed cluster finds nothing
        n0.api.executor.serving.audit.quarantine.clear()
        n0.audit_scrub(budget=16)
        assert not n0.api.executor.serving.audit.quarantine
    finally:
        for n in nodes:
            n.close()


# ---------------------------------------------------------------------------
# HTTP + federation surface
# ---------------------------------------------------------------------------

def _req(port, method, path, body=None):
    import http.client
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    data = json.dumps(body) if isinstance(body, (dict, list)) else body
    c.request(method, path, body=data,
              headers={"Content-Type": "application/json"})
    r = c.getresponse()
    raw = r.read()
    c.close()
    return r.status, json.loads(raw or b"{}")


def test_debug_audit_http_and_federation():
    from pilosa_tpu.cluster import ClusterNode, InMemDisCo
    node = ClusterNode("n0", InMemDisCo(lease_ttl=30), replica_n=1,
                       heartbeat_interval=30).open()
    try:
        # AFTER open: server startup applies the default [audit] config
        audit.configure(sample_rate=1.0)
        node.apply_schema({"indexes": [{"name": "c", "fields": [
            {"name": "f", "options": {"type": "set"}}]}]})
        node.import_bits("c", "f", [1, 1], [0, 1])
        node.query("c", "Count(Row(f=1))")
        srv = node.api.executor.serving
        assert srv.audit.wait_idle(30)
        port = node.server.port
        st, d = _req(port, "GET", "/debug/audit")
        assert st == 200 and d["enabled"] and d["active"]
        assert d["sample_rate"] == 1.0
        assert any(k.startswith("shadow:") for k in d["counts"])
        st, d = _req(port, "GET", "/debug/cluster/audit")
        assert st == 200 and not d["partial"]
        assert d["nodes"] == ["n0"]
        assert d["per_node"]["n0"]["active"]
        # the audited-flight filter over HTTP
        st, d = _req(port, "GET", "/debug/queries?audited=1")
        assert st == 200
        assert d["queries"] and all(r["audited"]
                                    for r in d["queries"])
    finally:
        node.close()
