"""Config layering + Arrow dataframe tests."""

import pytest

from pilosa_tpu import config as cfgmod
from pilosa_tpu.models.dataframe import DataframeError, IndexDataframe


def test_config_layering(tmp_path):
    p = tmp_path / "c.toml"
    p.write_text(
        'data-dir = "/var/data"\n'
        'port = 7777\n'
        '[cluster]\nreplicas = 3\n'
        '[auth]\nsecret = "filesec"\n'
        '[tpu]\nkernels = "off"\n')
    # file only
    cfg = cfgmod.load(str(p), env={})
    assert cfg.data_dir == "/var/data"
    assert cfg.port == 7777
    assert cfg.replicas == 3
    assert cfg.auth_secret == "filesec"
    assert cfg.tpu_kernels == "off"
    # env overrides file
    cfg = cfgmod.load(str(p), env={"PILOSA_TPU_PORT": "8888",
                                   "PILOSA_TPU_AUTH_SECRET": "envsec"})
    assert cfg.port == 8888 and cfg.auth_secret == "envsec"
    # flags override env
    cfg = cfgmod.load(str(p), env={"PILOSA_TPU_PORT": "8888"},
                      overrides={"port": 9999, "bind": None})
    assert cfg.port == 9999
    assert cfg.bind == "127.0.0.1"  # None override ignored
    # defaults without file
    assert cfgmod.load(env={}).port == 10101


def test_config_kernel_setting(monkeypatch):
    import os
    monkeypatch.delenv("PILOSA_TPU_PALLAS", raising=False)
    cfg = cfgmod.Config(tpu_kernels="on")
    cfg.apply_kernel_setting()
    assert os.environ["PILOSA_TPU_PALLAS"] == "1"
    # auto leaves a user-exported override untouched
    cfg = cfgmod.Config(tpu_kernels="auto")
    cfg.apply_kernel_setting()
    assert os.environ["PILOSA_TPU_PALLAS"] == "1"
    cfg = cfgmod.Config(tpu_kernels="off")
    cfg.apply_kernel_setting()
    assert os.environ["PILOSA_TPU_PALLAS"] == "0"
    monkeypatch.delenv("PILOSA_TPU_PALLAS", raising=False)


def test_dataframe_rows_and_apply(tmp_path):
    df = IndexDataframe(str(tmp_path))
    df.add_rows([{"_id": 1, "price": 10.0, "qty": 3},
                 {"_id": 2, "price": 2.5, "qty": 8},
                 {"_id": 3, "price": 4.0}])
    assert df.n_rows == 3
    types = {s["name"]: s["type"] for s in df.schema()}
    assert types["price"] == "float" and types["qty"] == "int"
    # ragged column null-filled
    assert df.column("qty").tolist() == [3, 8, None]
    # row-aligned computed column (apply.go Apply capability)
    got = df.apply("price * qty")
    assert got == [30.0, 20.0, 0.0]
    # reducing expression through the whitelisted function table
    assert df.apply("sum(price)") == 16.5
    with pytest.raises(DataframeError):
        df.apply("__import__('os')")
    with pytest.raises(DataframeError):
        df.column("nope")


def test_dataframe_device_aggregate(tmp_path):
    df = IndexDataframe(str(tmp_path))
    df.add_rows([{"_id": i, "v": i * 2} for i in range(100)])
    assert df.aggregate("sum", "v") == 2 * sum(range(100))
    assert df.aggregate("min", "v") == 0
    assert df.aggregate("max", "v") == 198
    assert df.aggregate("count", "v") == 100
    assert df.aggregate("mean", "v") == pytest.approx(99.0)
    with pytest.raises(DataframeError):
        df.aggregate("median", "v")


def test_dataframe_parquet_roundtrip(tmp_path):
    df = IndexDataframe(str(tmp_path))
    df.add_rows([{"_id": 1, "a": "x"}, {"_id": 2, "a": "y"}])
    df.save()
    df2 = IndexDataframe(str(tmp_path))
    assert df2.n_rows == 2
    assert df2.column("a").tolist() == ["x", "y"]
    assert df2.to_arrow().num_rows == 2


def test_dataframe_http_routes():
    from pilosa_tpu.cluster.client import InternalClient, RemoteError
    from pilosa_tpu.server.http import Server

    srv = Server().start()
    uri = f"127.0.0.1:{srv.port}"
    cli = InternalClient()
    try:
        cli._request(uri, "POST", "/index/dfi", {})
        r = cli._request(uri, "POST", "/index/dfi/dataframe", {
            "rows": [{"_id": 1, "x": 5}, {"_id": 2, "x": 7}]})
        assert r["rows"] == 2
        r = cli._request(uri, "GET", "/index/dfi/dataframe")
        assert any(s["name"] == "x" for s in r["schema"])
        r = cli._request(uri, "POST", "/index/dfi/dataframe/apply",
                         {"expr": "x + 1"})
        assert r["result"] == [6, 8]
        r = cli._request(uri, "POST", "/index/dfi/dataframe/apply",
                         {"aggregate": "sum", "column": "x"})
        assert r["result"] == 12
        with pytest.raises(RemoteError) as e:
            cli._request(uri, "POST", "/index/nope/dataframe", {})
        assert e.value.status == 404
    finally:
        srv.close()


def test_float_config_coercion():
    """Float settings (long-query-time) coerce from flags/env/TOML —
    not silently stringified (regression: _coerce lacked a float
    branch)."""
    from pilosa_tpu import config as cfgmod
    cfg = cfgmod.load(overrides={"long_query_time": 0.25})
    assert cfg.long_query_time == 0.25
    cfg = cfgmod.load(env={"PILOSA_TPU_LONG_QUERY_TIME": "1.5"})
    assert cfg.long_query_time == 1.5
