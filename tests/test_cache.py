"""TopN rank/LRU cache tests (cache.go behavior)."""

import numpy as np

from pilosa_tpu.models.cache import LRUCache, RankCache, make_cache
from pilosa_tpu.models.fragment import Fragment
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.models.schema import FieldOptions, FieldType

WIDTH = 1 << 12


def test_rank_cache_orders_and_prunes():
    c = RankCache(max_entries=10)
    for r in range(30):
        c.add(r, r + 1)
    top = c.top()
    # pruned to max_entries, highest counts kept
    assert len(c) <= 11  # threshold factor slack
    assert top[0] == (29, 30)
    assert all(top[i][1] >= top[i + 1][1] for i in range(len(top) - 1))
    # below-threshold rows are not admitted once full
    c.add(100, 1)
    assert c.count(100) == 0
    # zero count removes
    c.add(29, 0)
    assert c.count(29) == 0


def test_lru_cache_evicts_by_recency():
    c = LRUCache(max_entries=3)
    for r in (1, 2, 3):
        c.add(r, 10 * r)
    c.add(1, 11)  # touch 1 -> 2 is now oldest
    c.add(4, 40)
    assert c.count(2) == 0
    assert {r for r, _ in c.top()} == {1, 3, 4}


def test_make_cache_types():
    assert isinstance(make_cache("ranked"), RankCache)
    assert isinstance(make_cache("lru"), LRUCache)
    assert make_cache("none") is None
    try:
        make_cache("bogus")
        assert False
    except ValueError:
        pass


def test_fragment_cache_tracks_mutations():
    f = Fragment("i", "f", "standard", 0, width=WIDTH,
                 cache_type="ranked")
    for col in range(5):
        f.set_bit(1, col)
    f.set_bit(2, 0)
    cache = f.row_cache()
    assert cache.top()[0] == (1, 5)
    assert cache.count(2) == 1
    f.clear_bit(1, 0)
    assert f.row_cache().count(1) == 4
    # clearing a row entirely drops it from the cache
    f.clear_bit(2, 0)
    assert f.row_cache().count(2) == 0
    # bulk import updates too
    f.import_bits([7] * 3, [1, 2, 3])
    assert f.row_cache().count(7) == 3


def test_fragment_cache_none():
    f = Fragment("i", "f", "standard", 0, width=WIDTH)
    f.set_bit(1, 1)
    assert f.row_cache() is None


def test_topn_uses_cache_and_matches_exact(rng):
    h = Holder(width=WIDTH)
    idx = h.create_index("t")
    fld = idx.create_field("f", FieldOptions(type=FieldType.SET))
    rows = rng.integers(0, 20, size=500)
    cols = rng.integers(0, 4 * WIDTH, size=500)
    for r, c in zip(rows, cols):
        fld.set_bit(int(r), int(c))
    idx.mark_columns_exist([int(c) for c in cols])
    from pilosa_tpu.executor.executor import Executor
    ex = Executor(h)
    got = ex.execute("t", "TopN(f, n=5)")[0]
    # ground truth by exact per-row count of distinct columns
    want = {}
    for r in range(20):
        want[r] = len({int(c) for rr, c in zip(rows, cols) if rr == r})
    best = sorted(want.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
    assert [(p.id, p.count) for p in got] == best
    # ids= path stays exact and includes zero-count rows
    got_ids = ex.execute("t", "TopN(f, ids=[0,1,99])")[0]
    assert {p.id for p in got_ids} == {0, 1, 99}


def test_topn_cache_respects_lru_field_option():
    h = Holder(width=WIDTH)
    idx = h.create_index("t2")
    fld = idx.create_field(
        "f", FieldOptions(type=FieldType.SET, cache_type="lru",
                          cache_size=2))
    # 3 rows; lru size 2 -> oldest row falls out of TopN entirely
    fld.set_bit(1, 0)
    fld.set_bit(2, 1)
    fld.set_bit(3, 2)
    idx.mark_columns_exist([0, 1, 2])
    from pilosa_tpu.executor.executor import Executor
    got = Executor(h).execute("t2", "TopN(f)")[0]
    assert {p.id for p in got} == {2, 3}


def test_lru_refresh_preserves_write_order():
    # ids chosen so hash order != write order would expose set-order
    # refresh; the ordered stale dict must preserve recency
    f = Fragment("i", "f", "standard", 0, width=WIDTH,
                 cache_type="lru", cache_size=2)
    order = [1 << 40, 3, 1 << 20]
    for i, r in enumerate(order):
        f.set_bit(r, i)
    cache = f.row_cache()
    # first-written row evicted, last two survive
    assert set(cache.ids()) == {3, 1 << 20}


def test_filtered_topn_bounded_by_ranked_cache(rng):
    """Filtered TopN on a ranked-cache field scans only the cache's
    candidate rows (fragment.go:1317 / cache.go:130 strategy): with a
    covering cache the result is EXACTLY the full scan's."""
    from pilosa_tpu.executor.executor import Executor
    from pilosa_tpu.models.schema import CACHE_TYPE_NONE

    rows = rng.integers(0, 30, size=800)
    cols = rng.integers(0, 3 * WIDTH, size=800)

    def build(**kw):
        h = Holder(width=WIDTH)
        idx = h.create_index("t")
        fld = idx.create_field("f", FieldOptions(type=FieldType.SET,
                                                 **kw))
        g = idx.create_field("g", FieldOptions(type=FieldType.SET))
        for r, c in zip(rows, cols):
            fld.set_bit(int(r), int(c))
            g.set_bit(int(c) % 2, int(c))
        idx.mark_columns_exist([int(c) for c in cols])
        return h

    ha = build()  # default ranked cache (covering: 50k >> 30 rows)
    hb = build(cache_type=CACHE_TYPE_NONE)  # exact full scan
    ea, eb = Executor(ha), Executor(hb)
    q = "TopN(f, Row(g=1), n=8)"
    got = [(p.id, p.count) for p in ea.execute("t", q)[0]]
    want = [(p.id, p.count) for p in eb.execute("t", q)[0]]
    assert got == want
