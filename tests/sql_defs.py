"""Declarative SQL conformance definitions.

Models the reference's table-driven sql3/test/defs suites
(sql3/test/defs/defs_groupby.go, defs_join.go, defs_subquery tests,
executed by sql3/sql_test.go:34): each case is pure data — setup SQL,
one query, and the expected rows — executed by
tests/test_sql_conformance.py against a fresh engine.

Case tuple: (name, sql, expected) where expected is
- a list of row tuples  -> compared as a multiset (order-free)
- ("ordered", [rows])   -> compared in order (ORDER BY cases)
- ("error", "substr")   -> SQLError whose message contains substr
- an int                -> single-cell result (scalar shorthand)
"""

from decimal import Decimal

# Shared schema + data every case starts from.
SETUP = [
    """CREATE TABLE orders (
         _id id, region string, status string, qty int,
         price decimal(2), tags stringset, paid bool, cust int)""",
    """INSERT INTO orders (_id, region, status, qty, price, tags, paid, cust)
       VALUES
        (1, 'west',  'open',   5,  '10.50', ('a','b'), true,  10),
        (2, 'west',  'closed', 12, '3.25',  ('b'),     false, 10),
        (3, 'east',  'open',   7,  '99.99', ('a','c'), true,  20),
        (4, 'east',  'open',   2,  '1.00',  ('c'),     false, 30),
        (5, 'north', 'closed', 12, '0.75',  ('a'),     true,  99),
        (6, 'south', 'open',   null, null,  ('b','c'), true,  20)""",
    """CREATE TABLE customers (
         _id id, name string, region string, credit int)""",
    """INSERT INTO customers (_id, name, region, credit) VALUES
        (10, 'alice', 'west', 100),
        (20, 'bob',   'east', 50),
        (30, 'carol', 'east', 9)""",
]

D = Decimal

CASES = [
    # ---- meta / DDL -----------------------------------------------------
    # SHOW TABLES: the reference's 9-column listing (defs_sql1);
    # untracked audit fields are empty/epoch
    ("show_tables", "SELECT name, keys FROM nope; SHOW TABLES",
     ("error", "nope")),
    ("show_tables_names", "SHOW TABLES",
     [(None, "customers", "", "", "1970-01-01T00:00:00Z",
       "1970-01-01T00:00:00Z", False, 0, ""),
      (None, "orders", "", "", "1970-01-01T00:00:00Z",
       "1970-01-01T00:00:00Z", False, 0, "")]),
    # SHOW COLUMNS: the reference's 14-column listing — compare the
    # (name, type) slice through a projectionless check here
    ("show_columns_types",
     "SHOW COLUMNS FROM customers",
     [(None, "_id", "id", "1970-01-01T00:00:00Z", False, "", 0, 0,
       None, None, "", 0, "", ""),
      (None, "name", "string", "1970-01-01T00:00:00Z", True,
       "ranked", 50000, 0, None, None, "", 0, "", ""),
      (None, "region", "string", "1970-01-01T00:00:00Z", True,
       "ranked", 50000, 0, None, None, "", 0, "", ""),
      (None, "credit", "int", "1970-01-01T00:00:00Z", False,
       "ranked", 50000, 0, None, None, "", 0, "", "")]),
    ("create_if_not_exists",
     "CREATE TABLE IF NOT EXISTS orders (_id id, x int); "
     "SELECT count(*) FROM orders", 6),
    ("create_duplicate_errors",
     "CREATE TABLE orders (_id id, x int)", ("error", "exists")),
    ("drop_if_exists_missing",
     "DROP TABLE IF EXISTS nope; SHOW COLUMNS FROM customers",
     [(None, "_id", "id", "1970-01-01T00:00:00Z", False, "", 0, 0,
       None, None, "", 0, "", ""),
      (None, "name", "string", "1970-01-01T00:00:00Z", True,
       "ranked", 50000, 0, None, None, "", 0, "", ""),
      (None, "region", "string", "1970-01-01T00:00:00Z", True,
       "ranked", 50000, 0, None, None, "", 0, "", ""),
      (None, "credit", "int", "1970-01-01T00:00:00Z", False,
       "ranked", 50000, 0, None, None, "", 0, "", "")]),
    ("drop_then_gone",
     "DROP TABLE customers; SHOW COLUMNS FROM customers",
     ("error", "customers")),
    ("unknown_table_errors", "SELECT * FROM nope", ("error", "nope")),
    ("unknown_column_errors", "SELECT bogus FROM orders",
     ("error", "bogus")),

    # ---- INSERT ---------------------------------------------------------
    ("insert_adds_row",
     "INSERT INTO orders (_id, qty) VALUES (7, 1); "
     "SELECT count(*) FROM orders", 7),
    ("insert_or_replace_overwrites",
     "INSERT OR REPLACE INTO orders (_id, region, qty) "
     "VALUES (1, 'moved', 3); "
     "SELECT region, qty FROM orders WHERE _id = 1", [("moved", 3)]),
    ("replace_clears_old_values",
     "REPLACE INTO orders (_id, qty) VALUES (1, 8); "
     "SELECT region FROM orders WHERE _id = 1", [(None,)]),
    ("insert_arity_mismatch",
     "INSERT INTO orders (_id, qty) VALUES (9, 1, 2)",
     ("error", "mismatch in the count of expressions")),
    ("insert_requires_id",
     "INSERT INTO orders (qty) VALUES (1)", ("error", "_id")),
    ("insert_unknown_column",
     "INSERT INTO orders (_id, nope) VALUES (9, 1)", ("error", "nope")),

    # ---- WHERE: int comparisons ----------------------------------------
    ("int_eq", "SELECT _id FROM orders WHERE qty = 12", [(2,), (5,)]),
    ("int_neq", "SELECT _id FROM orders WHERE qty != 12",
     [(1,), (3,), (4,)]),
    ("int_lt", "SELECT _id FROM orders WHERE qty < 5", [(4,)]),
    ("int_lte", "SELECT _id FROM orders WHERE qty <= 5", [(1,), (4,)]),
    ("int_gt", "SELECT _id FROM orders WHERE qty > 7", [(2,), (5,)]),
    ("int_gte", "SELECT _id FROM orders WHERE qty >= 7",
     [(2,), (3,), (5,)]),
    ("int_literal_on_left", "SELECT _id FROM orders WHERE 7 < qty",
     [(2,), (5,)]),
    ("int_between", "SELECT _id FROM orders WHERE qty BETWEEN 5 AND 7",
     [(1,), (3,)]),
    ("int_not_between",
     "SELECT _id FROM orders WHERE qty NOT BETWEEN 5 AND 7",
     [(2,), (4,), (5,)]),
    ("is_null_int", "SELECT _id FROM orders WHERE qty IS NULL", [(6,)]),
    ("is_not_null_int", "SELECT _id FROM orders WHERE qty IS NOT NULL",
     [(1,), (2,), (3,), (4,), (5,)]),
    ("is_null_string", "SELECT _id FROM orders WHERE region IS NULL", []),

    # ---- WHERE: IN / LIKE ----------------------------------------------
    ("in_int", "SELECT _id FROM orders WHERE qty IN (2, 5)", [(1,), (4,)]),
    # strict SQL: NULL NOT IN (...) is UNKNOWN, so row 6 is excluded
    ("not_in_int", "SELECT _id FROM orders WHERE qty NOT IN (2, 5, 7)",
     [(2,), (5,)]),
    ("in_string", "SELECT _id FROM orders WHERE region IN ('east','north')",
     [(3,), (4,), (5,)]),
    ("like_suffix", "SELECT _id FROM orders WHERE region LIKE '%st'",
     [(1,), (2,), (3,), (4,)]),
    ("like_prefix", "SELECT _id FROM orders WHERE region LIKE 'we%'",
     [(1,), (2,)]),
    ("like_underscore", "SELECT _id FROM orders WHERE region LIKE '_est'",
     [(1,), (2,)]),
    ("not_like", "SELECT _id FROM orders WHERE region NOT LIKE '%st'",
     [(5,), (6,)]),

    # ---- WHERE: bool / decimal / string / sets / _id --------------------
    ("bool_true", "SELECT _id FROM orders WHERE paid = true",
     [(1,), (3,), (5,), (6,)]),
    ("bool_neq", "SELECT _id FROM orders WHERE paid != true",
     [(2,), (4,)]),
    ("decimal_lt", "SELECT _id FROM orders WHERE price < 2",
     [(4,), (5,)]),
    ("decimal_gte", "SELECT _id FROM orders WHERE price >= 10.50",
     [(1,), (3,)]),
    ("decimal_eq", "SELECT _id FROM orders WHERE price = 3.25", [(2,)]),
    ("decimal_between",
     "SELECT _id FROM orders WHERE price BETWEEN 1 AND 11",
     [(1,), (2,), (4,)]),
    ("string_eq", "SELECT _id FROM orders WHERE status = 'open'",
     [(1,), (3,), (4,), (6,)]),
    ("string_neq", "SELECT _id FROM orders WHERE status != 'open'",
     [(2,), (5,)]),
    ("set_membership", "SELECT _id FROM orders WHERE tags = 'a'",
     [(1,), (3,), (5,)]),
    ("set_not_member", "SELECT _id FROM orders WHERE tags != 'a'",
     [(2,), (4,), (6,)]),
    ("set_in", "SELECT _id FROM orders WHERE tags IN ('a', 'c')",
     [(1,), (3,), (4,), (5,), (6,)]),
    ("id_eq", "SELECT region FROM orders WHERE _id = 3", [("east",)]),
    ("id_neq", "SELECT count(*) FROM orders WHERE _id != 3", 5),
    ("id_in", "SELECT _id FROM orders WHERE _id IN (1, 4, 999)",
     [(1,), (4,)]),

    # ---- logical combinators -------------------------------------------
    ("and_", "SELECT _id FROM orders WHERE region = 'east' AND paid = true",
     [(3,)]),
    ("or_", "SELECT _id FROM orders WHERE qty = 2 OR qty = 5",
     [(1,), (4,)]),
    ("not_", "SELECT _id FROM orders WHERE NOT status = 'open'",
     [(2,), (5,)]),
    ("precedence_and_over_or",
     "SELECT _id FROM orders "
     "WHERE region = 'west' AND qty = 5 OR region = 'north'",
     [(1,), (5,)]),
    ("parens_override",
     "SELECT _id FROM orders "
     "WHERE region = 'west' AND (qty = 5 OR qty = 12)",
     [(1,), (2,)]),

    # ---- aggregates -----------------------------------------------------
    ("count_star", "SELECT count(*) FROM orders", 6),
    ("count_col_skips_null", "SELECT count(qty) FROM orders", 5),
    ("count_distinct_int", "SELECT count(distinct qty) FROM orders", 4),
    ("count_distinct_string",
     "SELECT count(distinct status) FROM orders", 2),
    ("sum_int", "SELECT sum(qty) FROM orders", 38),
    ("min_int", "SELECT min(qty) FROM orders", 2),
    ("max_int", "SELECT max(qty) FROM orders", 12),
    ("sum_decimal", "SELECT sum(price) FROM orders",
     [(D("115.49"),)]),
    ("min_decimal", "SELECT min(price) FROM orders", [(D("0.75"),)]),
    ("agg_with_where",
     "SELECT sum(qty) FROM orders WHERE region = 'west'", 17),
    ("count_where_empty",
     "SELECT count(*) FROM orders WHERE qty > 100", 0),

    # ---- GROUP BY / HAVING ---------------------------------------------
    ("groupby_count",
     "SELECT status, count(*) FROM orders GROUP BY status",
     [("open", 4), ("closed", 2)]),
    ("groupby_sum",
     # groups with no SUM rows are dropped (defs_groupby
     # groupByTests_6; executor.go GroupBy aggregate filtering) —
     # south's only row has NULL qty
     "SELECT region, sum(qty) FROM orders GROUP BY region",
     [("west", 17), ("east", 9), ("north", 12)]),
    ("groupby_two_cols",
     "SELECT region, status, count(*) FROM orders "
     "GROUP BY region, status",
     [("west", "open", 1), ("west", "closed", 1), ("east", "open", 2),
      ("north", "closed", 1), ("south", "open", 1)]),
    # records NULL in a group column form no group (defs_sql1
    # grouper semantics; matches the PQL GroupBy member-based path)
    ("groupby_int_col",
     "SELECT qty, count(*) FROM orders GROUP BY qty",
     [(2, 1), (5, 1), (7, 1), (12, 2)]),
    ("groupby_where",
     "SELECT status, count(*) FROM orders WHERE region = 'east' "
     "GROUP BY status", [("open", 2)]),
    ("groupby_having_count",
     "SELECT status, count(*) FROM orders GROUP BY status "
     "HAVING count(*) > 2", [("open", 4)]),
    ("groupby_having_sum",
     "SELECT region, sum(qty) FROM orders GROUP BY region "
     "HAVING sum(qty) >= 12", [("west", 17), ("north", 12)]),
    ("groupby_set_column",
     # SQL groups a SET column by its FULL set value (defs_groupby
     # groupByTests_14), unlike the member-wise PQL GroupBy pushdown
     "SELECT tags, count(*) FROM orders GROUP BY tags",
     [(["a", "b"], 1), (["b"], 1), (["a", "c"], 1), (["c"], 1),
      (["a"], 1), (["b", "c"], 1)]),

    # ---- ORDER BY / LIMIT / OFFSET / DISTINCT ---------------------------
    ("order_by_asc",
     "SELECT _id FROM orders WHERE qty IS NOT NULL ORDER BY qty",
     ("ordered", [(4,), (1,), (3,), (2,), (5,)])),
    ("order_by_desc",
     "SELECT _id, qty FROM orders WHERE qty >= 7 ORDER BY qty DESC, _id",
     ("ordered", [(2, 12), (5, 12), (3, 7)])),
    ("order_by_string",
     "SELECT region FROM orders WHERE _id IN (1, 3, 5) ORDER BY region",
     ("ordered", [("east",), ("north",), ("west",)])),
    ("limit_", "SELECT _id FROM orders ORDER BY _id LIMIT 2",
     ("ordered", [(1,), (2,)])),
    ("limit_offset", "SELECT _id FROM orders ORDER BY _id LIMIT 2 OFFSET 3",
     ("ordered", [(4,), (5,)])),
    ("distinct_string", "SELECT DISTINCT status FROM orders",
     [("closed",), ("open",)]),
    ("distinct_int", "SELECT DISTINCT qty FROM orders",
     [(2,), (5,), (7,), (12,)]),
    ("distinct_with_where",
     "SELECT DISTINCT region FROM orders WHERE paid = true",
     [("east",), ("north",), ("south",), ("west",)]),

    # ---- projections ----------------------------------------------------
    ("select_columns",
     "SELECT region, qty FROM orders WHERE _id = 2", [("west", 12)]),
    ("select_star_shape",
     # '*' expands to _id + fields in DECLARATION order (defs_keyed)
     "SELECT * FROM orders WHERE _id = 4",
     [(4, "east", "open", 2, D("1.00"), ["c"], False, 30)]),
    ("select_alias",
     "SELECT qty AS n FROM orders WHERE _id = 1", [(5,)]),
    ("empty_result", "SELECT _id FROM orders WHERE region = 'mars'", []),

    # ---- JOIN -----------------------------------------------------------
    ("inner_join_basic",
     "SELECT orders._id, customers.name FROM orders "
     "INNER JOIN customers ON orders.cust = customers._id",
     [(1, "alice"), (2, "alice"), (3, "bob"), (4, "carol"),
      (6, "bob")]),
    ("inner_join_where_right",
     "SELECT orders._id FROM orders "
     "INNER JOIN customers ON orders.cust = customers._id "
     "WHERE customers.region = 'east'", [(3,), (4,), (6,)]),
    ("inner_join_where_both",
     "SELECT orders._id FROM orders "
     "JOIN customers ON orders.cust = customers._id "
     "WHERE customers.credit >= 50 AND orders.paid = true",
     [(1,), (3,), (6,)]),
    ("inner_join_count",
     "SELECT count(*) FROM orders "
     "INNER JOIN customers ON orders.cust = customers._id", 5),
    ("left_join_keeps_unmatched",
     "SELECT orders._id, customers.name FROM orders "
     "LEFT JOIN customers ON orders.cust = customers._id",
     [(1, "alice"), (2, "alice"), (3, "bob"), (4, "carol"),
      (5, None), (6, "bob")]),
    ("left_outer_join_spelled",
     "SELECT count(*) FROM orders "
     "LEFT OUTER JOIN customers ON orders.cust = customers._id", 6),
    ("left_join_anti_join",
     "SELECT orders._id FROM orders "
     "LEFT JOIN customers ON orders.cust = customers._id "
     "WHERE customers._id IS NULL", [(5,)]),
    ("left_join_where_filters_nulls",
     "SELECT orders._id FROM orders "
     "LEFT JOIN customers ON orders.cust = customers._id "
     "WHERE customers.credit > 40", [(1,), (2,), (3,), (6,)]),
    ("join_unqualified_on_errors",
     "SELECT _id FROM orders JOIN customers ON cust = _id",
     ("error", "qualified")),

    # ---- subqueries -----------------------------------------------------
    ("in_subquery",
     "SELECT _id FROM orders WHERE cust IN "
     "(SELECT _id FROM customers WHERE region = 'east')",
     [(3,), (4,), (6,)]),
    ("not_in_subquery",
     "SELECT _id FROM orders WHERE cust NOT IN "
     "(SELECT _id FROM customers WHERE region = 'east')",
     [(1,), (2,), (5,)]),
    ("in_subquery_same_table",
     "SELECT _id FROM orders WHERE qty IN "
     "(SELECT qty FROM orders WHERE region = 'north')", [(2,), (5,)]),
    ("scalar_subquery_max",
     "SELECT _id FROM orders WHERE qty = (SELECT max(qty) FROM orders)",
     [(2,), (5,)]),
    ("scalar_subquery_cross_table",
     "SELECT name FROM customers WHERE credit = "
     "(SELECT max(credit) FROM customers)", [("alice",)]),
    ("scalar_subquery_empty_matches_nothing",
     "SELECT _id FROM orders WHERE qty = "
     "(SELECT max(qty) FROM orders WHERE region = 'mars')", []),
    ("scalar_subquery_multirow_errors",
     "SELECT _id FROM orders WHERE qty = "
     "(SELECT qty FROM orders WHERE region = 'west')",
     ("error", "more than one row")),
    ("subquery_multicolumn_errors",
     "SELECT _id FROM orders WHERE qty IN "
     "(SELECT _id, qty FROM orders)", ("error", "one column")),

    # ---- BULK INSERT ----------------------------------------------------
    ("bulk_insert_stream",
     "BULK INSERT INTO orders (_id, region, qty) "
     "FROM '20,mars,9\n21,mars,3' WITH FORMAT 'CSV' INPUT 'STREAM'; "
     "SELECT _id, qty FROM orders WHERE region = 'mars'",
     [(20, 9), (21, 3)]),
    ("bulk_insert_header_row",
     "BULK INSERT INTO orders (_id, region, qty) "
     "FROM '_id,region,qty\n22,venus,4' "
     "WITH FORMAT 'CSV' INPUT 'STREAM' HEADER_ROW; "
     "SELECT qty FROM orders WHERE region = 'venus'", [(4,)]),
    ("bulk_insert_null_cells",
     "BULK INSERT INTO orders (_id, region, qty) "
     "FROM '23,,7' WITH FORMAT 'CSV' INPUT 'STREAM'; "
     "SELECT region, qty FROM orders WHERE _id = 23", [(None, 7)]),
    ("bulk_insert_set_list",
     "BULK INSERT INTO orders (_id, tags) "
     "FROM '24,a;c' WITH FORMAT 'CSV' INPUT 'STREAM'; "
     "SELECT _id FROM orders WHERE tags = 'c'", [(3,), (4,), (6,), (24,)]),
    ("bulk_insert_returns_no_rows",
     "BULK INSERT INTO orders (_id, qty) "
     "FROM '30,1\n31,2\n32,3' WITH FORMAT 'CSV' INPUT 'STREAM'",
     []),
    ("bulk_insert_arity_errors",
     "BULK INSERT INTO orders (_id, region, qty) "
     "FROM '25,x' WITH FORMAT 'CSV' INPUT 'STREAM'", ("error", "fields")),
    ("bulk_insert_bad_format_errors",
     "BULK INSERT INTO orders (_id) FROM 'x' "
     "WITH FORMAT 'JSON' INPUT 'STREAM'", ("error", "CSV")),

    # ---- views ----------------------------------------------------------
    ("create_view_and_select",
     "CREATE VIEW open_orders AS SELECT _id, qty FROM orders "
     "WHERE status = 'open'; "
     "SELECT _id FROM open_orders", [(1,), (3,), (4,), (6,)]),
    ("view_star_and_order",
     "CREATE VIEW oq AS SELECT _id, qty FROM orders "
     "WHERE qty IS NOT NULL; "
     "SELECT * FROM oq ORDER BY qty DESC, _id LIMIT 2",
     ("ordered", [(2, 12), (5, 12)])),
    ("view_reflects_new_data",
     "CREATE VIEW ov AS SELECT count(*) FROM orders; "
     "INSERT INTO orders (_id, qty) VALUES (50, 1); "
     "SELECT * FROM ov", 7),
    ("show_views",
     "CREATE VIEW v1 AS SELECT _id FROM orders; SHOW VIEWS",
     [("v1",)]),
    ("drop_view",
     "CREATE VIEW v1 AS SELECT _id FROM orders; DROP VIEW v1; "
     "SHOW VIEWS", []),
    ("drop_view_missing_errors", "DROP VIEW nope",
     ("error", "view not found")),
    ("view_name_collision_errors",
     "CREATE VIEW orders AS SELECT _id FROM orders",
     ("error", "exists")),
    ("view_where_unsupported",
     "CREATE VIEW v2 AS SELECT _id, qty FROM orders; "
     "SELECT _id FROM v2 WHERE qty > 1",
     ("error", "projection/ORDER BY/LIMIT")),

    # ---- regression lockdowns (r03 review findings) ----------------------
    ("multikey_order_limit_sorts_before_limit",
     "SELECT _id, qty FROM orders WHERE qty IS NOT NULL "
     "ORDER BY qty, _id LIMIT 2",
     ("ordered", [(4, 2), (1, 5)])),
    ("not_in_subquery_with_null_is_empty",
     "SELECT _id FROM orders WHERE qty NOT IN "
     "(SELECT qty FROM orders)", []),
    ("contextual_keywords_stay_identifiers",
     "CREATE TABLE kwtest (_id id, input int, format string); "
     "INSERT INTO kwtest (_id, input, format) VALUES (1, 5, 'x'); "
     "SELECT input, format FROM kwtest", [(5, "x")]),
    ("bulk_insert_missing_file_is_sql_error",
     "BULK INSERT INTO orders (_id, qty) FROM '/no/such/file.csv' "
     "WITH FORMAT 'CSV' INPUT 'FILE'", ("error", "cannot read")),

    # ---- DELETE ---------------------------------------------------------
    ("delete_where",
     "DELETE FROM orders WHERE region = 'west'; "
     "SELECT count(*) FROM orders", 4),
    ("delete_by_id",
     "DELETE FROM orders WHERE _id = 6; "
     "SELECT _id FROM orders WHERE region = 'south'", []),
    ("delete_all",
     "DELETE FROM orders; SELECT count(*) FROM orders", 0),

    # ---- scalar functions: string (inbuiltfunctionsstring.go) -----------
    ("fn_upper_lower",
     "SELECT UPPER(region), LOWER(status) FROM orders WHERE _id = 1",
     [("WEST", "open")]),
    ("fn_reverse", "SELECT REVERSE(region) FROM orders WHERE _id = 3",
     [("tsae",)]),
    ("fn_len_in_where", "SELECT _id FROM orders WHERE LEN(region) = 5",
     [(5,), (6,)]),
    ("fn_substring",
     "SELECT SUBSTRING(region, 0, 2) FROM orders WHERE _id = 5",
     [("no",)]),
    ("fn_substring_no_len",
     "SELECT SUBSTRING(region, 1) FROM orders WHERE _id = 5",
     [("orth",)]),
    ("fn_substring_out_of_range",
     "SELECT SUBSTRING(region, 99) FROM orders WHERE _id = 5",
     ("error", "out of range")),
    ("fn_char_ascii",
     "SELECT CHAR(119), ASCII('w') FROM orders WHERE _id = 1",
     [("w", 119)]),
    ("fn_charindex",
     "SELECT CHARINDEX('s', region), CHARINDEX('zz', region) "
     "FROM orders WHERE _id = 1", [(2, -1)]),
    ("fn_trim_family",
     "SELECT TRIM('  x  '), LTRIM('  x'), RTRIM('x  ') "
     "FROM orders WHERE _id = 1", [("x", "x", "x")]),
    ("fn_prefix_suffix",
     "SELECT PREFIX(region, 2), SUFFIX(region, 2) "
     "FROM orders WHERE _id = 1", [("we", "st")]),
    ("fn_replicate_space",
     "SELECT REPLICATE('ab', 3), LEN(SPACE(4)) "
     "FROM orders WHERE _id = 1", [("ababab", 4)]),
    ("fn_replaceall",
     "SELECT REPLACEALL(region, 'w', 'b') FROM orders WHERE _id = 1",
     [("best",)]),
    ("fn_stringsplit",
     "SELECT STRINGSPLIT('a,b,c', ','), STRINGSPLIT('a,b,c', ',', 2), "
     "STRINGSPLIT('a,b,c', ',', 9) FROM orders WHERE _id = 1",
     [("a", "c", "")]),
    ("fn_format",
     "SELECT FORMAT('%s-%d', region, qty) FROM orders WHERE _id = 2",
     [("west-12",)]),
    ("fn_str",
     "SELECT STR(qty, 4), STR(qty, 2) FROM orders WHERE _id = 2",
     [("  12", "12")]),
    ("fn_nested",
     "SELECT UPPER(SUBSTRING(region, 0, 1)) || LOWER(SUFFIX(region, 3)) "
     "FROM orders WHERE _id = 1", [("West",)]),
    ("fn_null_propagates",
     "INSERT INTO orders (_id, qty) VALUES (7, 1); "
     "SELECT UPPER(region) FROM orders WHERE _id = 7", [(None,)]),
    ("fn_unknown_errors",
     "SELECT NOSUCHFN(region) FROM orders", ("error", "NOSUCHFN")),

    ("fn_charindex_with_pos",
     "SELECT CHARINDEX('s', 'mississippi', 4), "
     "CHARINDEX('s', 'mississippi', 7) FROM orders WHERE _id = 1",
     [(5, -1)]),
    ("fn_str_overflow_renders_stars",
     "SELECT STR(12345, 3) FROM orders WHERE _id = 1", [("***",)]),
    ("fn_str_decimals",
     "SELECT STR(price, 6, 1) FROM orders WHERE _id = 1",
     [("  10.5",)]),
    ("fn_replicate_zero", "SELECT REPLICATE('ab', 0) "
     "FROM orders WHERE _id = 1", [("",)]),
    ("fn_substring_full_tail",
     "SELECT SUBSTRING(region, 0) FROM orders WHERE _id = 1",
     [("west",)]),
    ("fn_ascii_multichar_errors",
     "SELECT ASCII(region) FROM orders WHERE _id = 1",
     ("error", "should be of the length 1")),
    ("fn_arity_validated_before_null",
     # NULL args must not mask an arity error (r03 review)
     "INSERT INTO orders (_id, qty) VALUES (8, 1); "
     "SELECT SUBSTRING(region, 1, 2, 3) FROM orders WHERE _id = 8",
     ("error", "arguments")),

    # ---- scalar functions: datetime (inbuiltfunctionsdate.go) -----------
    ("fn_datetimepart",
     "SELECT DATETIMEPART('YY', '2024-05-06T07:08:09'), "
     "DATETIMEPART('M', '2024-05-06T07:08:09'), "
     "DATETIMEPART('D', '2024-05-06T07:08:09'), "
     "DATETIMEPART('HH', '2024-05-06T07:08:09') "
     "FROM orders WHERE _id = 1", [(2024, 5, 6, 7)]),
    ("fn_datetimename_month",
     "SELECT DATETIMENAME('M', '2024-05-06T07:08:09') "
     "FROM orders WHERE _id = 1", [("May",)]),
    ("fn_date_trunc",
     # DATE_TRUNC returns the truncated prefix string
     # (defs_date_functions dateTruncTests)
     "SELECT DATE_TRUNC('M', '2024-05-06T07:08:09') "
     "FROM orders WHERE _id = 1", [("2024-05",)]),
    ("fn_datetimeadd",
     "SELECT DATETIMEADD('D', 3, '2024-05-06T07:08:09'), "
     "DATETIMEADD('M', 2, '2024-12-31T00:00:00'), "
     "DATETIMEADD('YY', 1, '2024-02-29T00:00:00') "
     "FROM orders WHERE _id = 1",
     [("2024-05-09T07:08:09Z", "2025-03-03T00:00:00Z",
       "2025-03-01T00:00:00Z")]),
    ("fn_datetimediff",
     "SELECT DATETIMEDIFF('D', '2024-05-01T00:00:00', "
     "'2024-05-06T12:00:00'), DATETIMEDIFF('YY', "
     "'2020-01-01T00:00:00', '2024-05-06T00:00:00') "
     "FROM orders WHERE _id = 1", [(5, 4)]),
    ("fn_datetimefromparts",
     "SELECT DATETIMEFROMPARTS(2024, 5, 6, 7, 8, 9, 250) "
     "FROM orders WHERE _id = 1", [("2024-05-06T07:08:09.250000Z",)]),
    ("fn_totimestamp",
     "SELECT TOTIMESTAMP(86400), TOTIMESTAMP(1000, 'ms') "
     "FROM orders WHERE _id = 1",
     [("1970-01-02T00:00:00Z", "1970-01-01T00:00:01Z")]),
    ("fn_bad_interval",
     "SELECT DATETIMEPART('XX', '2024-05-06T07:08:09') FROM orders",
     ("error", "interval")),
    ("fn_datetimepart_week_and_weekday",
     # 2024-05-06 is a Monday: Go Weekday()=1, ISO week 19, yearday 127
     "SELECT DATETIMEPART('W', '2024-05-06T00:00:00'), "
     "DATETIMEPART('WK', '2024-05-06T00:00:00'), "
     "DATETIMEPART('YD', '2024-05-06T00:00:00') "
     "FROM orders WHERE _id = 1", [(1, 19, 127)]),
    ("fn_datetimename_weekday",
     "SELECT DATETIMENAME('W', '2024-05-06T00:00:00') "
     "FROM orders WHERE _id = 1", [("Monday",)]),
    ("fn_datetimediff_negative",
     # reversed operands give a negative diff (b - a)
     "SELECT DATETIMEDIFF('D', '2024-05-06T00:00:00', "
     "'2024-05-01T00:00:00') FROM orders WHERE _id = 1", [(-5,)]),
    ("fn_date_trunc_year",
     "SELECT DATE_TRUNC('YY', '2024-05-06T07:08:09') "
     "FROM orders WHERE _id = 1", [("2024",)]),
    ("fn_totimestamp_us",
     "SELECT TOTIMESTAMP(1500000, 'us') FROM orders WHERE _id = 1",
     [("1970-01-01T00:00:01.500000Z",)]),

    # ---- scalar functions: set (inbuiltfunctionsset.go) -----------------
    ("fn_setcontains",
     "SELECT _id FROM orders WHERE SETCONTAINS(tags, 'a')",
     [(1,), (3,), (5,)]),
    ("fn_setcontainsany",
     "SELECT _id FROM orders WHERE SETCONTAINSANY(tags, ('b', 'c'))",
     [(1,), (2,), (3,), (4,), (6,)]),
    ("fn_setcontainsall",
     "SELECT _id FROM orders WHERE SETCONTAINSALL(tags, ('a', 'c'))",
     [(3,)]),
    ("fn_setcontains_negated",
     "SELECT _id FROM orders WHERE NOT SETCONTAINS(tags, 'a') "
     "AND qty IS NOT NULL", [(2,), (4,)]),
    ("fn_setcontains_projection",
     "SELECT _id, SETCONTAINS(tags, 'a') FROM orders "
     "WHERE _id IN (1, 2)", [(1, True), (2, False)]),

    # ---- arithmetic + expression projections ----------------------------
    ("arith_projection",
     "SELECT _id, qty * 2 + 1 FROM orders WHERE _id IN (1, 4)",
     [(1, 11), (4, 5)]),
    ("arith_div_mod",
     "SELECT qty / 5, qty % 5 FROM orders WHERE _id = 2", [(2, 2)]),
    ("arith_div_zero",
     # defs_binops.go DivisionDivideByZeroRow message
     "SELECT qty / 0 FROM orders WHERE _id = 1",
     ("error", "divisor is equal to zero")),
    ("arith_in_where",
     "SELECT _id FROM orders WHERE qty * 2 = 24", [(2,), (5,)]),
    ("arith_null_propagates",
     "SELECT qty + 1 FROM orders WHERE _id = 6", [(None,)]),
    ("concat_projection",
     "SELECT region || '-' || status FROM orders WHERE _id = 1",
     [("west-open",)]),
    ("expr_mixing_pushed_and_residue",
     "SELECT _id FROM orders WHERE qty > 4 AND LEN(region) = 4",
     [(1,), (2,), (3,)]),
    ("order_by_expression",
     "SELECT _id FROM orders WHERE qty IS NOT NULL ORDER BY 0 - qty",
     ("ordered", [(2,), (5,), (3,), (1,), (4,)])),
    ("order_by_alias",
     "SELECT _id, qty * 2 AS dbl FROM orders WHERE qty IS NOT NULL "
     "ORDER BY dbl DESC LIMIT 2",
     ("ordered", [(2, 24), (5, 24)])),
    ("order_by_ordinal",
     # defs_orderby.go `order by 1 asc`
     "SELECT qty, _id FROM orders WHERE qty IS NOT NULL ORDER BY 1",
     ("ordered", [(2, 4), (5, 1), (7, 3), (12, 2), (12, 5)])),
    ("order_by_ordinal_multi",
     "SELECT region, qty FROM orders WHERE qty IS NOT NULL "
     "ORDER BY 1, 2 DESC",
     ("ordered", [("east", 7), ("east", 2), ("north", 12),
                  ("west", 12), ("west", 5)])),
    ("order_by_ordinal_out_of_range",
     "SELECT qty FROM orders ORDER BY 3", ("error", "out of range")),
    ("order_by_multi_unprojected",
     # defs_orderby.go `order by foo asc, a_decimal asc`: alias key +
     # an UNPROJECTED column key in one ORDER BY
     "SELECT qty AS foo, _id FROM orders WHERE qty IS NOT NULL "
     "ORDER BY foo, price DESC",
     ("ordered", [(2, 4), (5, 1), (7, 3), (12, 2), (12, 5)])),
    ("order_by_multi_expr_key",
     # qty % 5: id1->0, others->2; ties break by _id
     "SELECT _id FROM orders WHERE qty IS NOT NULL "
     "ORDER BY qty % 5, _id",
     ("ordered", [(1,), (2,), (3,), (4,), (5,)])),

    # ---- ALTER TABLE (compilealtertable.go) -----------------------------
    ("alter_add_column",
     "ALTER TABLE orders ADD COLUMN note string; "
     "INSERT INTO orders (_id, note) VALUES (9, 'hi'); "
     "SELECT note FROM orders WHERE _id = 9", [("hi",)]),
    ("alter_add_duplicate_errors",
     "ALTER TABLE orders ADD COLUMN qty int", ("error", "exists")),
    ("alter_drop_column",
     "ALTER TABLE orders DROP COLUMN tags; "
     "SELECT tags FROM orders", ("error", "tags")),
    ("alter_drop_missing_errors",
     "ALTER TABLE orders DROP COLUMN nope", ("error", "nope")),
    ("alter_rename_column",
     "ALTER TABLE orders RENAME COLUMN qty TO amount; "
     "SELECT _id FROM orders WHERE amount = 12", [(2,), (5,)]),
    ("alter_rename_keyed_column_keeps_keys",
     "ALTER TABLE orders RENAME COLUMN region TO zone; "
     "SELECT zone FROM orders WHERE _id = 1", [("west",)]),
    ("alter_rename_bsi_keeps_sum",
     "ALTER TABLE orders RENAME COLUMN qty TO amount; "
     "SELECT sum(amount) FROM orders", 38),
    ("alter_rename_to_existing_errors",
     "ALTER TABLE orders RENAME COLUMN qty TO region",
     ("error", "exists")),
    ("alter_unknown_table_errors",
     "ALTER TABLE nope ADD COLUMN x int", ("error", "nope")),

    ("fn_datetime_eq_string",
     "CREATE TABLE ev (_id id, ts timestamp); "
     "INSERT INTO ev (_id, ts) VALUES (1, '2024-05-06T07:08:09'), "
     "(2, '2024-05-07T01:00:00'); "
     "SELECT _id FROM ev WHERE DATE_TRUNC('D', ts) = "
     "'2024-05-06'", [(1,)]),

    # ---- SHOW CREATE TABLE ----------------------------------------------
    ("show_create_table_roundtrip",
     "SHOW CREATE TABLE customers",
     [("CREATE TABLE customers (_id id, name string, "
       "region string, credit int)",)]),

    # ---- CREATE FUNCTION (scalar-expression UDFs) -----------------------
    ("udf_projection",
     "CREATE FUNCTION shout(@s string) RETURNS string AS "
     "(UPPER(@s) || '!'); "
     "SELECT shout(region) FROM orders WHERE _id = 1", [("WEST!",)]),
    ("udf_in_where",
     "CREATE FUNCTION dbl(@x int) RETURNS int AS (@x * 2); "
     "SELECT _id FROM orders WHERE dbl(qty) = 24", [(2,), (5,)]),
    ("udf_calls_udf",
     "CREATE FUNCTION dbl(@x int) RETURNS int AS (@x * 2); "
     "CREATE FUNCTION quad(@x int) RETURNS int AS (dbl(dbl(@x))); "
     "SELECT quad(qty) FROM orders WHERE _id = 1", [(20,)]),
    ("udf_arity_error",
     "CREATE FUNCTION dbl(@x int) RETURNS int AS (@x * 2); "
     "SELECT dbl(qty, 1) FROM orders", ("error", "arguments")),
    ("udf_body_column_ref_errors",
     "CREATE FUNCTION bad(@x int) RETURNS int AS (qty + @x)",
     ("error", "parameters")),
    ("udf_builtin_shadow_errors",
     "CREATE FUNCTION upper(@s string) RETURNS string AS (@s)",
     ("error", "built-in")),
    ("udf_duplicate_errors",
     "CREATE FUNCTION f(@x int) RETURNS int AS (@x); "
     "CREATE FUNCTION f(@x int) RETURNS int AS (@x)",
     ("error", "exists")),
    ("udf_drop",
     "CREATE FUNCTION f(@x int) RETURNS int AS (@x); "
     "DROP FUNCTION f; SELECT f(qty) FROM orders", ("error", "F")),
    ("udf_show_functions",
     "CREATE FUNCTION dbl(@x int) RETURNS int AS (@x * 2); "
     "SHOW FUNCTIONS",
     [("dbl", "(@x int) returns int")]),
    ("udf_null_param",
     "CREATE FUNCTION dbl(@x int) RETURNS int AS (@x * 2); "
     "SELECT dbl(qty) FROM orders WHERE _id = 6", [(None,)]),
    # ---- time quantum: tuple INSERT + RANGEQ (opinsert.go:275,
    # expressionpql.go:99, inbuiltfunctionsquantum.go) --------------------
    ("quantum_insert_and_rangeq",
     "CREATE TABLE ev3 (_id id, sites idset timequantum 'YMD'); "
     "INSERT INTO ev3 (_id, sites) VALUES "
     "(1, ('2024-01-15T00:00:00', (7))), "
     "(2, ('2024-06-20T00:00:00', (7))); "
     "SELECT _id FROM ev3 WHERE "
     "RANGEQ(sites, '2024-01-01T00:00:00', '2024-02-01T00:00:00')",
     [(1,)]),
    ("rangeq_open_from",
     "CREATE TABLE ev3 (_id id, sites idset timequantum 'YMD'); "
     "INSERT INTO ev3 (_id, sites) VALUES "
     "(1, ('2024-01-15T00:00:00', (7))), "
     "(2, ('2024-06-20T00:00:00', (7))); "
     "SELECT _id FROM ev3 WHERE "
     "RANGEQ(sites, null, '2024-02-01T00:00:00')", [(1,)]),
    ("rangeq_both_null_errors",
     "CREATE TABLE ev3 (_id id, sites idset timequantum 'YMD'); "
     "SELECT _id FROM ev3 WHERE RANGEQ(sites, null, null)",
     ("error", "NULL")),
    ("rangeq_non_quantum_errors",
     "SELECT _id FROM orders WHERE "
     "RANGEQ(tags, '2024-01-01T00:00:00', null)",
     ("error", "timequantum")),
    ("rangeq_in_projection_errors",
     # evaluation-time error, like the reference's EvaluateRangeQ —
     # needs a row for the evaluator to reach the call
     "CREATE TABLE ev3 (_id id, sites idset timequantum 'YMD'); "
     "INSERT INTO ev3 (_id, sites) VALUES (1, (3)); "
     "SELECT RANGEQ(sites, '2024-01-01T00:00:00', null) FROM ev3",
     ("error", "WHERE filter")),
    ("quantum_insert_unix_seconds_timestamp",
     # int unix-seconds timestamps are accepted everywhere else
     # (timeq.parse_time), including here (r03 review)
     "CREATE TABLE ev3 (_id id, sites idset timequantum 'YMD'); "
     "INSERT INTO ev3 (_id, sites) VALUES (1, (1705276800, (7))); "
     "SELECT _id FROM ev3 WHERE "
     "RANGEQ(sites, '2024-01-01T00:00:00', '2024-02-01T00:00:00')",
     [(1,)]),
    ("quantum_plain_set_insert_still_works",
     "CREATE TABLE ev3 (_id id, sites idset timequantum 'YMD'); "
     "INSERT INTO ev3 (_id, sites) VALUES (1, (3, 4)); "
     "SELECT _id FROM ev3 WHERE SETCONTAINS(sites, 3)", [(1,)]),

    # ---- decimal bounds as strings (defs_between.go forms) --------------
    ("decimal_between_string_bounds",
     # prices in [1, 11]: 10.50 (1), 3.25 (2), 1.00 (4)
     "SELECT _id FROM orders WHERE price BETWEEN '1.00' AND '11.00'",
     [(1,), (2,), (4,)]),
    ("decimal_compare_string_bound",
     "SELECT _id FROM orders WHERE price > '3.00'",
     [(1,), (2,), (3,)]),
    ("decimal_bad_string_bound_errors",
     "SELECT _id FROM orders WHERE price > 'abc'",
     ("error", "numeric")),
    ("decimal_nonfinite_bound_errors",
     # 'NaN'/'Infinity' parse as Decimals but are not usable bounds
     "SELECT _id FROM orders WHERE price > 'NaN'",
     ("error", "finite")),
    ("decimal_infinity_bound_errors",
     "SELECT _id FROM orders WHERE price > 'Infinity'",
     ("error", "finite")),
    ("int_time_literal_bound_errors",
     "SELECT _id FROM orders WHERE qty > '2022-01-02T00:00:00'",
     ("error", "numeric")),

    # ---- keyed tables: string _id end-to-end (defs_keyed.go) ------------
    ("keyed_table_roundtrip",
     "CREATE TABLE users (_id string, region string, score int); "
     "INSERT INTO users (_id, region, score) VALUES "
     "('alice', 'west', 10), ('bob', 'east', 20), ('carol', 'west', 5); "
     "SELECT _id, score FROM users WHERE region = 'west' "
     "ORDER BY score DESC",
     ("ordered", [("alice", 10), ("carol", 5)])),
    ("keyed_table_id_filter",
     "CREATE TABLE users (_id string, score int); "
     "INSERT INTO users (_id, score) VALUES ('alice', 10), ('bob', 20); "
     "SELECT score FROM users WHERE _id = 'bob'", [(20,)]),
    ("keyed_table_id_in_list",
     "CREATE TABLE users (_id string, score int); "
     "INSERT INTO users (_id, score) VALUES "
     "('alice', 10), ('bob', 20), ('dora', 30); "
     "SELECT _id FROM users WHERE _id IN ('alice', 'dora', 'nope')",
     [("alice",), ("dora",)]),
    ("keyed_table_groupby_and_aggregate",
     "CREATE TABLE users (_id string, region string, score int); "
     "INSERT INTO users (_id, region, score) VALUES "
     "('alice', 'west', 10), ('bob', 'east', 20), ('carol', 'west', 5); "
     "SELECT region, sum(score) FROM users GROUP BY region",
     [("west", 15), ("east", 20)]),
    ("keyed_join_keyed",
     # join between two string-keyed tables on a keyed column
     "CREATE TABLE users (_id string, city string); "
     "CREATE TABLE cities (_id string, pop int); "
     "INSERT INTO users (_id, city) VALUES ('a', 'lyon'), ('b', 'nice'); "
     "INSERT INTO cities (_id, pop) VALUES ('lyon', 500), ('nice', 300); "
     "SELECT users._id, cities.pop FROM users "
     "INNER JOIN cities ON users.city = cities._id",
     [("a", 500), ("b", 300)]),
    ("keyed_table_delete_by_key",
     "CREATE TABLE users (_id string, score int); "
     "INSERT INTO users (_id, score) VALUES ('alice', 10), ('bob', 20); "
     "DELETE FROM users WHERE _id = 'alice'; "
     "SELECT _id FROM users", [("bob",)]),
    ("keyed_table_copy",
     "CREATE TABLE users (_id string, tag stringset); "
     "INSERT INTO users (_id, tag) VALUES ('a', ('x','y')), ('b', ('y')); "
     "COPY users TO users2; "
     "SELECT _id FROM users2 WHERE SETCONTAINS(tag, 'x')", [("a",)]),

    # ---- negative-range BSI columns (defs_minmaxnegative.go) ------------
    ("negative_int_roundtrip",
     "CREATE TABLE mm (_id id, p int min 10 max 100, "
     "n int min -100 max -10); "
     "INSERT INTO mm (_id, p, n) VALUES (1, 11, -11), (2, 22, -22), "
     "(3, 33, -33); "
     "SELECT _id, p, n FROM mm ORDER BY _id",
     ("ordered", [(1, 11, -11), (2, 22, -22), (3, 33, -33)])),
    ("negative_int_aggregates",
     "CREATE TABLE mm (_id id, n int min -100 max -10); "
     "INSERT INTO mm (_id, n) VALUES (1, -11), (2, -22), (3, -33); "
     "SELECT min(n), max(n), sum(n) FROM mm", [(-33, -11, -66)]),
    ("negative_int_range_filters",
     "CREATE TABLE mm (_id id, n int min -100 max -10); "
     "INSERT INTO mm (_id, n) VALUES (1, -11), (2, -22), (3, -33); "
     "SELECT _id FROM mm WHERE n < -15 AND n >= -33",
     [(2,), (3,)]),
    ("negative_int_order_by",
     "CREATE TABLE mm (_id id, n int min -100 max -10); "
     "INSERT INTO mm (_id, n) VALUES (1, -11), (2, -22), (3, -33); "
     "SELECT _id FROM mm ORDER BY n", ("ordered", [(3,), (2,), (1,)])),

    # ---- CAST + constant SELECT (defs_cast.go) --------------------------
    ("cast_int_to_bool", "SELECT CAST(1 AS bool), CAST(0 AS bool)",
     [(True, False)]),
    ("cast_int_to_decimal", "SELECT CAST(1 AS decimal(2))",
     [(D("1.00"),)]),
    ("cast_decimal_to_int_errors",
     # defs_cast castDecimal_0: decimal does not cast to int
     "SELECT CAST(price AS int) FROM orders WHERE _id = 1",
     ("error", "cannot be cast")),
    ("cast_string_to_int", "SELECT CAST('42' AS int)", [(42,)]),
    ("cast_bad_string_to_int_errors", "SELECT CAST('xx' AS int)",
     ("error", "cast")),
    ("cast_int_to_string", "SELECT CAST(7 AS string)", [("7",)]),
    ("cast_bool_to_string", "SELECT CAST(true AS string)",
     [("true",)]),
    ("cast_to_idset_errors", "SELECT CAST(1 AS idset)",
     ("error", "cast")),
    ("cast_int_to_timestamp", "SELECT CAST(86400 AS timestamp)",
     [("1970-01-02T00:00:00Z",)]),
    ("cast_string_to_timestamp",
     "SELECT CAST('2024-05-06T07:08:09' AS timestamp)",
     [("2024-05-06T07:08:09Z",)]),
    ("cast_null_is_null", "SELECT CAST(null AS int)", [(None,)]),
    ("cast_nonzero_int_to_bool_true",
     # defs_cast castInt_1: any non-zero int casts to true
     "SELECT CAST(7 AS bool)", [(True,)]),
    ("const_select_arithmetic", "SELECT 2 + 3 * 4, 'a' || 'b'",
     [(14, "ab")]),
    ("const_select_column_errors", "SELECT qty", ("error", "qty")),

    # ---- COPY (defs_copy.go) --------------------------------------------
    ("copy_table_roundtrip",
     "COPY orders TO orders2; "
     "SELECT region, qty, tags FROM orders2 WHERE _id = 1",
     [("west", 5, ["a", "b"])]),
    ("copy_preserves_counts",
     "COPY orders TO orders2; SELECT count(*) FROM orders2", 6),
    ("copy_missing_src_errors", "COPY nope TO x",
     ("error", "not found")),
    ("copy_existing_dst_errors", "COPY orders TO customers",
     ("error", "exists")),
    ("copy_then_independent_writes",
     "COPY orders TO orders2; "
     "DELETE FROM orders2 WHERE region = 'west'; "
     "SELECT count(*) FROM orders2; SELECT count(*) FROM orders",
     6),
    ("copy_preserves_quantum_views",
     "CREATE TABLE ev4 (_id id, sites idset timequantum 'YMD'); "
     "INSERT INTO ev4 (_id, sites) VALUES "
     "(1, ('2024-01-15T00:00:00', (7))), "
     "(2, ('2024-06-20T00:00:00', (7))); "
     "COPY ev4 TO ev5; "
     "SELECT _id FROM ev5 WHERE "
     "RANGEQ(sites, '2024-01-01T00:00:00', '2024-02-01T00:00:00')",
     [(1,)]),

    # ---- ALTER VIEW -----------------------------------------------------
    ("alter_view_replaces_definition",
     "CREATE VIEW v AS SELECT _id FROM orders WHERE qty = 12; "
     "ALTER VIEW v AS SELECT _id FROM orders WHERE qty = 5; "
     "SELECT _id FROM v", [(1,)]),
    ("alter_view_missing_errors",
     "ALTER VIEW nope AS SELECT _id FROM orders",
     ("error", "not found")),

    # ---- VAR / CORR aggregates (expressionagg.go:949,1197) --------------
    ("agg_var",
     # qty over non-null rows: 5,12,7,2,12 -> mean 7.6, pop. var 15.44
     "SELECT var(qty) FROM orders", [(D("15.440000"),)]),
    ("agg_var_filtered",
     # west: 5,12 -> mean 8.5, var 12.25
     "SELECT var(qty) FROM orders WHERE region = 'west'",
     [(D("12.250000"),)]),
    ("agg_corr",
     # corr(qty, cust) over rows with both: perfectly computable pair
     "SELECT corr(qty, qty) FROM orders", [(D("1.000000"),)]),
    ("agg_var_non_numeric_errors",
     "SELECT var(region) FROM orders",
     ("error", "integer or decimal expression expected")),
    ("agg_var_empty_is_null",
     "SELECT var(qty) FROM orders WHERE qty > 999", [(None,)]),

    # ---- TOP(n) (defs_top.go: TOP(n) == LIMIT n, conflict errors) -------
    ("top_rows", "SELECT TOP(2) _id FROM orders ORDER BY _id",
     ("ordered", [(1,), (2,)])),
    ("top_equals_limit",
     "SELECT TOP(1) count(*) FROM orders", [(6,)]),
    ("top_with_groupby",
     "SELECT TOP(10) region, count(*) FROM orders GROUP BY region",
     [("west", 2), ("east", 2), ("north", 1), ("south", 1)]),
    ("top_and_limit_conflict",
     "SELECT TOP(1) count(*) FROM orders LIMIT 1",
     ("error", "TOP and LIMIT")),
    ("top_fractional_errors",
     "SELECT TOP(2.5) _id FROM orders", ("error", "integer")),
    ("limit_fractional_errors",
     "SELECT _id FROM orders LIMIT 1.5", ("error", "integer")),
    ("top_as_column_name",
     # TOP not followed by '(' stays an ordinary projection position
     "CREATE TABLE topt (_id id, qty int); "
     "INSERT INTO topt (_id, qty) VALUES (1, 3); "
     "SELECT TOP(1) qty FROM topt", [(3,)]),

    # ---- EXPLAIN --------------------------------------------------------
    ("explain_returns_plan_rows",
     "EXPLAIN SELECT count(*) FROM orders WHERE qty > 4",
     [("filter pushdown (PQL, shard-parallel device scan): "
       "Row(qty > 4)",),
      ("aggregate pushdown: count(*)",)]),
    ("explain_groupby_pushdown",
     "EXPLAIN SELECT region, count(*) FROM orders GROUP BY region",
     [("filter pushdown (PQL, shard-parallel device scan): All()",),
      ("PQL GroupBy pushdown (stacked device program): Rows(region)",)]),
    ("explain_does_not_execute",
     "EXPLAIN DELETE FROM orders; SELECT count(*) FROM orders", 6),
    ("explain_does_not_run_subqueries",
     # a subquery against a MISSING table must not error under
     # EXPLAIN — subqueries evaluate at execution time only
     "EXPLAIN SELECT _id FROM orders WHERE qty = "
     "(SELECT max(qty) FROM nope)",
     [("filter pushdown (PQL, shard-parallel device scan): "
       "(contains subqueries — evaluated at execution time)",),
      ("Extract scan (device row materialization)",)]),
    ("explain_distinct_id_matches_execution",
     # DISTINCT _id takes the row-scan path, not the Distinct scan
     "EXPLAIN SELECT DISTINCT _id FROM orders",
     [("filter pushdown (PQL, shard-parallel device scan): All()",),
      ("Extract scan (device row materialization)",)]),
    ("agg_var_star_errors",
     "SELECT var(*) FROM orders", ("error", "column")),
    ("agg_var_timestamp_errors",
     "CREATE TABLE ev2 (_id id, ts timestamp); "
     "SELECT var(ts) FROM ev2",
     ("error", "integer or decimal expression expected")),
    ("agg_corr_constant_is_null",
     # zero variance -> undefined correlation -> NULL, never a crash
     "SELECT corr(cust, qty) FROM orders WHERE region = 'mars'",
     [(None,)]),

    ("udf_drop_recreate_cannot_cycle",
     # callees bind at CREATE time: re-creating g in terms of f must
     # not make the existing f recursive (r03 review)
     "CREATE FUNCTION g(@x int) RETURNS int AS (@x); "
     "CREATE FUNCTION f(@x int) RETURNS int AS (g(@x)); "
     "DROP FUNCTION g; "
     "CREATE FUNCTION g(@x int) RETURNS int AS (f(@x)); "
     "SELECT f(qty), g(qty) FROM orders WHERE _id = 1", [(5, 5)]),
]
