"""Packed-bitmap kernel tests vs set-algebra ground truth.

Mirrors the reference's container-op test approach (roaring tests vs
naive.go) on a small shard width for speed.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from pilosa_tpu.ops import bitmap as bm

W = 1 << 12  # small shard width for tests (bits); multiple of 32


def randcols(rng, n, width=W):
    return np.unique(rng.integers(0, width, size=n))


def test_pack_roundtrip(rng):
    cols = randcols(rng, 500)
    words = bm.from_columns(cols, W)
    assert words.shape == (W // 32,)
    np.testing.assert_array_equal(bm.to_columns(words), cols.astype(np.uint64))


def test_pack_empty():
    words = bm.from_columns([], W)
    assert bm.to_columns(words).size == 0
    assert int(bm.count(jnp.asarray(words))) == 0
    assert not bool(bm.any_set(jnp.asarray(words)))


@pytest.mark.parametrize("opname,setop", [
    ("intersect", lambda a, b: a & b),
    ("union", lambda a, b: a | b),
    ("difference", lambda a, b: a - b),
    ("xor", lambda a, b: a ^ b),
])
def test_set_ops(rng, opname, setop):
    a = set(randcols(rng, 700).tolist())
    b = set(randcols(rng, 700).tolist())
    wa = jnp.asarray(bm.from_columns(sorted(a), W))
    wb = jnp.asarray(bm.from_columns(sorted(b), W))
    got = getattr(bm, opname)(wa, wb)
    expect = setop(a, b)
    assert set(bm.to_columns(np.asarray(got)).tolist()) == expect
    assert int(bm.count(got)) == len(expect)


def test_complement_difference_full(rng):
    a = set(randcols(rng, 300).tolist())
    wa = jnp.asarray(bm.from_columns(sorted(a), W))
    full = jnp.asarray(bm.from_columns(range(W), W))
    got = bm.intersect(bm.complement(wa), full)
    assert set(bm.to_columns(np.asarray(got)).tolist()) == set(range(W)) - a


def test_intersection_count(rng):
    a = set(randcols(rng, 900).tolist())
    b = set(randcols(rng, 900).tolist())
    wa = jnp.asarray(bm.from_columns(sorted(a), W))
    wb = jnp.asarray(bm.from_columns(sorted(b), W))
    assert int(bm.intersection_count(wa, wb)) == len(a & b)


@pytest.mark.parametrize("n", [1, 7, 31, 32, 33, 64, 100, W - 1, W, W + 5])
def test_shift(rng, n):
    a = randcols(rng, 200).tolist()
    wa = jnp.asarray(bm.from_columns(a, W))
    got = bm.shift(wa, n)
    expect = {c + n for c in a if c + n < W}
    assert set(bm.to_columns(np.asarray(got)).tolist()) == expect


def test_shift_zero(rng):
    a = randcols(rng, 50).tolist()
    wa = jnp.asarray(bm.from_columns(a, W))
    np.testing.assert_array_equal(np.asarray(bm.shift(wa, 0)), np.asarray(wa))


@pytest.mark.parametrize("start,end", [
    (0, 0), (0, W), (5, 5), (0, 31), (0, 32), (1, 33), (31, 97),
    (64, 128), (100, 2000), (W - 33, W), (W - 1, W),
])
def test_count_range_and_mask(rng, start, end):
    a = randcols(rng, 800).tolist()
    wa = jnp.asarray(bm.from_columns(a, W))
    expect = sum(1 for c in a if start <= c < end)
    assert int(bm.count_range(wa, start, end)) == expect
    mask = bm.range_mask(start, end, W)
    assert set(bm.to_columns(mask).tolist()) == set(range(start, end))


def test_batched_ops(rng):
    """Ops broadcast over a leading row axis — the vmap-free batch path."""
    rows = [set(randcols(rng, 300).tolist()) for _ in range(6)]
    stack = jnp.asarray(
        np.stack([bm.from_columns(sorted(r), W) for r in rows]))
    counts = np.asarray(bm.count(stack))
    assert counts.tolist() == [len(r) for r in rows]
    u = bm.union_rows(stack)
    assert set(bm.to_columns(np.asarray(u)).tolist()) == set().union(*rows)
    i = bm.intersect_rows(stack)
    assert set(bm.to_columns(np.asarray(i)).tolist()) == set.intersection(*rows)
