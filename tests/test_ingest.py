"""Ingest tests — batcher, CSV/datagen sources, pipeline semantics
(reference: batch/batch.go, idk/ingest.go loop behaviors)."""

import io

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.ingest import (
    APIImporter,
    Batch,
    CSVSource,
    DatagenSource,
    KafkaSource,
    Pipeline,
    Record,
)
from pilosa_tpu.models.holder import Holder


@pytest.fixture()
def api():
    return API(Holder())


def test_batch_bits_and_values(api):
    api.apply_schema({"indexes": [{"name": "b", "fields": [
        {"name": "f", "options": {"type": "set"}},
        {"name": "n", "options": {"type": "int", "min": 0, "max": 100}},
    ]}]})
    b = Batch(APIImporter(api), "b",
              {"f": {"type": "set"}, "n": {"type": "int"}}, size=3)
    assert not b.add(Record(id=1, values={"f": 7, "n": 10}))
    assert not b.add(Record(id=2, values={"f": [7, 8], "n": 20}))
    assert b.add(Record(id=3, values={"n": None}))  # full at 3
    b.flush()
    [res] = api.query("b", "Count(Row(f=7))")["results"]
    assert res == 2
    [res] = api.query("b", "Sum(field=n)")["results"]
    assert res == {"value": 30, "count": 2}
    # record 3 had no f value and a null n: no bits anywhere
    [res] = api.query("b", "Count(Row(f=8))")["results"]
    assert res == 1


def test_batch_keyed_translation(api):
    api.apply_schema({"indexes": [{"name": "k", "keys": True, "fields": [
        {"name": "color", "options": {"type": "set", "keys": True}},
    ]}]})
    b = Batch(APIImporter(api), "k", {"color": {"type": "set", "keys": True}},
              size=10, index_keys=True)
    b.add(Record(id="alice", values={"color": "red"}))
    b.add(Record(id="bob", values={"color": ["red", "blue"]}))
    b.flush()
    [res] = api.query("k", 'Row(color="red")')["results"]
    assert sorted(res["keys"]) == ["alice", "bob"]


def test_csv_source_and_pipeline(api):
    csv = io.StringIO(
        "_id,segment:id,name:string,qty:int,ok:bool,tags:stringset\n"
        "1,3,aaa,10,true,x;y\n"
        "2,3,bbb,20,false,y\n"
        "3,4,,30,true,\n")
    src = CSVSource(csv)
    assert src.schema["qty"]["type"] == "int"
    assert src.schema["name"]["keys"] is True
    p = Pipeline(src, APIImporter(api), "c")
    assert p.run() == 3
    [res] = api.query("c", "Count(Row(segment=3))")["results"]
    assert res == 2
    [res] = api.query("c", "Sum(field=qty)")["results"]
    assert res == {"value": 60, "count": 3}
    [res] = api.query("c", 'Count(Row(tags="y"))')["results"]
    assert res == 2
    [res] = api.query("c", "Count(Row(ok=true))")["results"]
    assert res == 2
    # record 3's empty name → no bit
    [res] = api.query("c", "Count(Row(segment=4))")["results"]
    assert res == 1


def test_csv_keyed_ids(api):
    csv = io.StringIO("_id:string,seg:id\nuserA,1\nuserB,1\n")
    src = CSVSource(csv)
    p = Pipeline(src, APIImporter(api), "ck")
    assert p.run() == 2
    [res] = api.query("ck", "Row(seg=1)")["results"]
    assert sorted(res["keys"]) == ["userA", "userB"]


def test_csv_bad_header():
    with pytest.raises(ValueError):
        CSVSource(io.StringIO("_id,x:bogustype\n1,2\n"))
    with pytest.raises(ValueError):
        CSVSource(io.StringIO("x:id\n1\n"))  # no _id


def test_datagen_deterministic(api):
    src1 = list(DatagenSource(50, seed=7))
    src2 = list(DatagenSource(50, seed=7))
    assert [r.values for r in src1] == [r.values for r in src2]


def test_pipeline_concurrency_matches_serial(api):
    p1 = Pipeline(DatagenSource(500, seed=3), APIImporter(api), "s1",
                  batch_size=64, concurrency=1)
    p1.run()
    p4 = Pipeline(DatagenSource(500, seed=3), APIImporter(api), "s4",
                  batch_size=64, concurrency=4)
    assert p4.run() == 500
    for q in ("Count(Row(segment=5))", "Sum(field=amount)",
              "Count(Row(active=true))"):
        r1 = api.query("s1", q)["results"]
        r4 = api.query("s4", q)["results"]
        assert r1 == r4, q


def test_pipeline_small_batches_flush_all(api):
    p = Pipeline(DatagenSource(97, seed=1), APIImporter(api), "sb",
                 batch_size=10)
    assert p.run() == 97
    [res] = api.query("sb", "Count(All())")["results"]
    assert res == 97


def test_kafka_gated():
    with pytest.raises(NotImplementedError):
        KafkaSource("broker:9092")


def test_csv_time_field_with_ts(api):
    csv = io.StringIO(
        "_id,ev:time,_ts\n"
        "1,7,2020-03-15T10:00:00\n"
        "2,7,2021-06-01T00:00:00\n")
    src = CSVSource(csv)
    assert src.schema["ev"]["type"] == "time"
    p = Pipeline(src, APIImporter(api), "tv")
    assert p.run() == 2
    [r] = api.query("tv", "Count(Row(ev=7))")["results"]
    assert r == 2
    [r] = api.query(
        "tv", "Count(Row(ev=7, from='2020-01-01T00:00', to='2020-12-31T00:00'))"
    )["results"]
    assert r == 1


def test_pipeline_worker_error_raises_not_hangs(api):
    class BadSource(DatagenSource):
        def __iter__(self):
            for i in range(10000):
                yield Record(id="not-an-int", values={"segment": 1})
    src = BadSource(1)
    src.id_keys = False  # force int(id) failure in every batch
    p = Pipeline(src, APIImporter(api), "bad", batch_size=5, concurrency=3)
    with pytest.raises((ValueError, TypeError)):
        p.run()
