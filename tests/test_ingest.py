"""Ingest tests — batcher, CSV/datagen sources, pipeline semantics
(reference: batch/batch.go, idk/ingest.go loop behaviors)."""

import io

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.ingest import (
    APIImporter,
    Batch,
    CSVSource,
    DatagenSource,
    KafkaSource,
    Pipeline,
    Record,
)
from pilosa_tpu.models.holder import Holder


@pytest.fixture()
def api():
    return API(Holder())


def test_batch_bits_and_values(api):
    api.apply_schema({"indexes": [{"name": "b", "fields": [
        {"name": "f", "options": {"type": "set"}},
        {"name": "n", "options": {"type": "int", "min": 0, "max": 100}},
    ]}]})
    b = Batch(APIImporter(api), "b",
              {"f": {"type": "set"}, "n": {"type": "int"}}, size=3)
    assert not b.add(Record(id=1, values={"f": 7, "n": 10}))
    assert not b.add(Record(id=2, values={"f": [7, 8], "n": 20}))
    assert b.add(Record(id=3, values={"n": None}))  # full at 3
    b.flush()
    [res] = api.query("b", "Count(Row(f=7))")["results"]
    assert res == 2
    [res] = api.query("b", "Sum(field=n)")["results"]
    assert res == {"value": 30, "count": 2}
    # record 3 had no f value and a null n: no bits anywhere
    [res] = api.query("b", "Count(Row(f=8))")["results"]
    assert res == 1


def test_batch_keyed_translation(api):
    api.apply_schema({"indexes": [{"name": "k", "keys": True, "fields": [
        {"name": "color", "options": {"type": "set", "keys": True}},
    ]}]})
    b = Batch(APIImporter(api), "k", {"color": {"type": "set", "keys": True}},
              size=10, index_keys=True)
    b.add(Record(id="alice", values={"color": "red"}))
    b.add(Record(id="bob", values={"color": ["red", "blue"]}))
    b.flush()
    [res] = api.query("k", 'Row(color="red")')["results"]
    assert sorted(res["keys"]) == ["alice", "bob"]


def test_csv_source_and_pipeline(api):
    csv = io.StringIO(
        "_id,segment:id,name:string,qty:int,ok:bool,tags:stringset\n"
        "1,3,aaa,10,true,x;y\n"
        "2,3,bbb,20,false,y\n"
        "3,4,,30,true,\n")
    src = CSVSource(csv)
    assert src.schema["qty"]["type"] == "int"
    assert src.schema["name"]["keys"] is True
    p = Pipeline(src, APIImporter(api), "c")
    assert p.run() == 3
    [res] = api.query("c", "Count(Row(segment=3))")["results"]
    assert res == 2
    [res] = api.query("c", "Sum(field=qty)")["results"]
    assert res == {"value": 60, "count": 3}
    [res] = api.query("c", 'Count(Row(tags="y"))')["results"]
    assert res == 2
    [res] = api.query("c", "Count(Row(ok=true))")["results"]
    assert res == 2
    # record 3's empty name → no bit
    [res] = api.query("c", "Count(Row(segment=4))")["results"]
    assert res == 1


def test_csv_keyed_ids(api):
    csv = io.StringIO("_id:string,seg:id\nuserA,1\nuserB,1\n")
    src = CSVSource(csv)
    p = Pipeline(src, APIImporter(api), "ck")
    assert p.run() == 2
    [res] = api.query("ck", "Row(seg=1)")["results"]
    assert sorted(res["keys"]) == ["userA", "userB"]


def test_csv_bad_header():
    with pytest.raises(ValueError):
        CSVSource(io.StringIO("_id,x:bogustype\n1,2\n"))
    with pytest.raises(ValueError):
        CSVSource(io.StringIO("x:id\n1\n"))  # no _id


def test_datagen_deterministic(api):
    src1 = list(DatagenSource(50, seed=7))
    src2 = list(DatagenSource(50, seed=7))
    assert [r.values for r in src1] == [r.values for r in src2]


def test_pipeline_concurrency_matches_serial(api):
    p1 = Pipeline(DatagenSource(500, seed=3), APIImporter(api), "s1",
                  batch_size=64, concurrency=1)
    p1.run()
    p4 = Pipeline(DatagenSource(500, seed=3), APIImporter(api), "s4",
                  batch_size=64, concurrency=4)
    assert p4.run() == 500
    for q in ("Count(Row(segment=5))", "Sum(field=amount)",
              "Count(Row(active=true))"):
        r1 = api.query("s1", q)["results"]
        r4 = api.query("s4", q)["results"]
        assert r1 == r4, q


def test_pipeline_small_batches_flush_all(api):
    p = Pipeline(DatagenSource(97, seed=1), APIImporter(api), "sb",
                 batch_size=10)
    assert p.run() == 97
    [res] = api.query("sb", "Count(All())")["results"]
    assert res == 97


def test_kafka_gated():
    with pytest.raises(NotImplementedError):
        KafkaSource("broker:9092")


def test_csv_time_field_with_ts(api):
    csv = io.StringIO(
        "_id,ev:time,_ts\n"
        "1,7,2020-03-15T10:00:00\n"
        "2,7,2021-06-01T00:00:00\n")
    src = CSVSource(csv)
    assert src.schema["ev"]["type"] == "time"
    p = Pipeline(src, APIImporter(api), "tv")
    assert p.run() == 2
    [r] = api.query("tv", "Count(Row(ev=7))")["results"]
    assert r == 2
    [r] = api.query(
        "tv", "Count(Row(ev=7, from='2020-01-01T00:00', to='2020-12-31T00:00'))"
    )["results"]
    assert r == 1


def test_pipeline_worker_error_raises_not_hangs(api):
    class BadSource(DatagenSource):
        def __iter__(self):
            for i in range(10000):
                yield Record(id="not-an-int", values={"segment": 1})
    src = BadSource(1)
    src.id_keys = False  # force int(id) failure in every batch
    p = Pipeline(src, APIImporter(api), "bad", batch_size=5, concurrency=3)
    with pytest.raises((ValueError, TypeError)):
        p.run()


def test_columnar_add_matches_record_path(api):
    """Batch.add_columns (the numpy fast path) produces the same index
    state as per-record adds — sets, mutex last-write-wins, string
    keys, int values, NULL cells (batch.go:753 semantics)."""
    import numpy as np
    schema = {"indexes": [{"name": "c", "fields": [
        {"name": "f", "options": {"type": "set"}},
        {"name": "m", "options": {"type": "mutex"}},
        {"name": "s", "options": {"type": "mutex", "keys": True}},
        {"name": "n", "options": {"type": "int", "min": 0,
                                  "max": 1000}},
    ]}]}
    api.apply_schema(schema)
    api2 = API(Holder())
    api2.apply_schema(schema)
    bschema = {"f": {"type": "set"}, "m": {"type": "mutex"},
               "s": {"type": "mutex", "keys": True},
               "n": {"type": "int"}}
    N = 500
    rng = np.random.default_rng(3)
    ids = np.arange(N)
    f = rng.integers(0, 9, size=N)
    m = rng.integers(0, 4, size=N)
    s = np.array([f"k{v}" for v in rng.integers(0, 7, size=N)],
                 dtype=object)
    n = rng.integers(0, 1000, size=N).astype(object)
    n[::7] = None  # NULL cells skip the bit
    colb = Batch(APIImporter(api), "c", bschema)
    colb.add_columns(ids, {"f": f, "m": m, "s": s, "n": n})
    recb = Batch(APIImporter(api2), "c", bschema, size=64)
    for i in range(N):
        recb.add(Record(int(ids[i]), {
            "f": int(f[i]), "m": int(m[i]), "s": str(s[i]),
            "n": None if n[i] is None else int(n[i])}))
        recb.flush()
    from pilosa_tpu.executor import Executor
    e1, e2 = Executor(api.holder), Executor(api2.holder)
    for q in ("Count(Row(f=3))", "Count(Row(m=2))",
              "Count(Row(s='k5'))", "Count(Row(n > 500))",
              "Count(All())"):
        r1 = e1.execute("c", q)[0]
        r2 = e2.execute("c", q)[0]
        assert r1 == r2, (q, r1, r2)


def test_import_columns_api_parallel_and_serial_agree(api):
    """API.import_columns: worker-threaded multi-field import equals
    the serial import, existence marked once."""
    import numpy as np
    schema = {"indexes": [{"name": "p", "fields": [
        {"name": "a", "options": {"type": "set"}},
        {"name": "b", "options": {"type": "set"}},
        {"name": "v", "options": {"type": "int", "min": 0,
                                  "max": 50}},
    ]}]}
    api.apply_schema(schema)
    api2 = API(Holder())
    api2.apply_schema(schema)
    N = 400
    rng = np.random.default_rng(5)
    ids = np.arange(N) * 3
    bits = {"a": rng.integers(0, 5, size=N),
            "b": rng.integers(0, 5, size=N)}
    vals = {"v": rng.integers(0, 50, size=N)}
    api.import_columns("p", ids, bits=bits, values=vals, workers=4)
    api2.import_columns("p", ids, bits=bits, values=vals, workers=1)
    from pilosa_tpu.executor import Executor
    e1, e2 = Executor(api.holder), Executor(api2.holder)
    for q in ("Count(All())", "Count(Row(a=1))", "Count(Row(b=4))",
              "Sum(field=v)"):
        assert e1.execute("p", q)[0] == e2.execute("p", q)[0], q


def _mp_ingest_worker(uri, index, shard_lo, shard_hi, per_shard):
    """Child-process ingester: disjoint shard range -> one server
    (the IDK clone shape, idk/ingest.go:302,319)."""
    import numpy as np

    from pilosa_tpu.ingest.importer import HTTPImporter
    W = 1 << 20
    imp = HTTPImporter(uri)
    total = 0
    for shard in range(shard_lo, shard_hi):
        cols = shard * W + np.arange(per_shard, dtype=np.int64)
        total += imp.import_columns(
            "mp", cols,
            bits={"m": (cols % 7)},
            values={"v": (cols % 1000)})
    return total


def test_multiprocess_sharded_ingest():
    """N importer PROCESSES over disjoint shard ranges into one
    server — the reference's IDK clone concurrency
    (idk/ingest.go:302 m.clone() per ingester).  Validates the
    deployment shape on this host; the measured single-process rate
    ladder lives in BENCH_TPU_NOTES.md."""
    import multiprocessing as mp

    from pilosa_tpu.server import Server
    srv = Server().start()
    try:
        uri = f"127.0.0.1:{srv.port}"
        from pilosa_tpu.ingest.importer import HTTPImporter
        HTTPImporter(uri).apply_schema({"indexes": [{
            "name": "mp", "fields": [
                {"name": "m", "options": {"type": "mutex"}},
                {"name": "v", "options": {"type": "int", "min": 0,
                                          "max": 1000}}]}]})
        n_workers, shards_per, per_shard = 3, 2, 5000
        ctx = mp.get_context("spawn")
        with ctx.Pool(n_workers) as pool:
            totals = pool.starmap(
                _mp_ingest_worker,
                [(uri, "mp", w * shards_per, (w + 1) * shards_per,
                  per_shard) for w in range(n_workers)])
        assert sum(totals) == n_workers * shards_per * per_shard * 2
        # every shard landed, disjointly owned by its importer
        import http.client
        import json as _json
        c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                       timeout=30)
        c.request("POST", "/index/mp/query",
                  body=_json.dumps({"query": "Count(Row(m=0))"}))
        got = _json.loads(c.getresponse().read())
        c.close()
        want = sum(1 for s in range(n_workers * shards_per)
                   for i in range(per_shard)
                   if (s * (1 << 20) + i) % 7 == 0)
        assert got["results"][0] == want
    finally:
        srv.close()


def test_import_values_int64_min_magnitude():
    """INT64_MIN roundtrips through the bulk BSI import: its magnitude
    2^63 only exists in uint64 (the native kernel's old signed
    negation was UB there, and np.abs is the identity), and the plane
    writes stay inside the declared depth."""
    import numpy as np

    from pilosa_tpu.models.fragment import Fragment
    from pilosa_tpu.ops import bsi as bsi_ops

    int64_min = -(1 << 63)
    depth = 64
    frag = Fragment("i", "v", "bsi", 0, width=1 << 12)
    frag.import_values([5, 9], [int64_min, 3], depth)
    planes = np.stack([frag.row_words(r) for r in range(2 + depth)])
    cols, vals = bsi_ops.decode(planes)
    assert cols.tolist() == [5, 9]
    assert vals == [int64_min, 3]


def test_import_values_numpy_fallback_int64_min(monkeypatch):
    """Same roundtrip with the toolchain absent (numpy scatter)."""
    import numpy as np

    from pilosa_tpu.models.fragment import Fragment
    from pilosa_tpu.ops import bsi as bsi_ops
    from pilosa_tpu.storage import native_ingest as ni

    monkeypatch.setattr(ni, "_lib", None)
    monkeypatch.setattr(ni, "_lib_failed", True)
    int64_min = -(1 << 63)
    frag = Fragment("i", "v", "bsi", 0, width=1 << 12)
    frag.import_values([7], [int64_min], 64)
    planes = np.stack([frag.row_words(r) for r in range(66)])
    cols, vals = bsi_ops.decode(planes)
    assert cols.tolist() == [7] and vals == [int64_min]


def test_import_values_depth_overflow_raises():
    """An out-of-depth magnitude is an unconditional error, not an
    assert that vanishes under python -O: it would otherwise reach the
    native kernel as an out-of-bounds plane index."""
    import pytest as _pytest

    from pilosa_tpu.models.fragment import Fragment

    frag = Fragment("i", "v", "bsi", 0, width=1 << 12)
    with _pytest.raises(ValueError, match="bits"):
        frag.import_values([1], [8], depth=3)
    # INT64_MIN against a too-shallow field must also raise, not wrap
    with _pytest.raises(ValueError, match="bits"):
        frag.import_values([1], [-(1 << 63)], depth=63)
