"""The reference def files' PQLTests, ported (REFERENCE TEST DATA:
sql3/test/defs/ PQLTest entries — defs_join.go distinctjoin,
defs_keyed.go minrow/maxrow/topk/rows/includescolumn/constrow/
fieldvalue/unionrows, defs_unkeyed.go options — run against their
TableTest setups through Executor.execute, the sql_test.go PQL
path)."""

import pytest

from pilosa_tpu.models import Holder
from pilosa_tpu.sql import SQLEngine

W = 1 << 12


def _engine(setups):
    eng = SQLEngine(Holder(width=W))
    for s in setups:
        eng.query(s)
    return eng


KEYED_SETUP = [
    "CREATE TABLE keyed (_id string, an_int int min 0 max 100, "
    "an_id_set idset, an_id id, a_string string, "
    "a_string_set stringset)",
    "INSERT INTO keyed (_id, an_int, an_id_set, an_id, a_string, "
    "a_string_set) VALUES "
    "('one', 11, (11, 12, 13), 101, 'str1', ('a1', 'b1', 'c1')), "
    "('two', 22, (11, 12, 23), 201, 'str2', ('a2', 'b2', 'c2')), "
    "('three', 33, (11, 32, 33), 301, 'str3', ('a3', 'b3', 'c3')), "
    "('four', 44, (41, 42, 43), 401, 'str4', ('a4', 'b4', 'c4'))",
]


@pytest.fixture(scope="module")
def keyed():
    return _engine(KEYED_SETUP)


def _pairs(res):
    if not isinstance(res, list):
        res = [res]
    return [(p.id, p.count) for p in res]


def test_minrow(keyed):
    # count is a has-value flag, not the row's column count
    # (fragment.go:858: "if filter is nil, it returns minRowID, 1")
    r = keyed.executor.execute("keyed", "MinRow(field=an_id_set)")[0]
    assert (r.id, r.count) == (11, 1)


def test_maxrow(keyed):
    r = keyed.executor.execute("keyed", "MaxRow(field=an_id_set)")[0]
    assert (r.id, r.count) == (43, 1)


def test_topk(keyed):
    r = keyed.executor.execute("keyed", "TopK(an_id_set, k=2)")[0]
    assert _pairs(r) == [(11, 3), (12, 2)]


def test_rows(keyed):
    r = keyed.executor.execute("keyed", "Rows(field=an_id_set)")[0]
    assert list(r) == [11, 12, 13, 23, 32, 33, 41, 42, 43]


def test_includescolumn(keyed):
    r = keyed.executor.execute(
        "keyed", "IncludesColumn(Row(an_id_set=12), column='two')")[0]
    assert r is True


def test_constrow_extract_keyed(keyed):
    # ConstRow takes column KEYS on a keyed index (preTranslate)
    r = keyed.executor.execute(
        "keyed", "Extract(ConstRow(columns=['two']), Rows(an_id))")[0]
    assert [(e["column_key"], e["rows"][0]) for e in r.columns] == \
        [("two", 201)]


def test_fieldvalue(keyed):
    r = keyed.executor.execute(
        "keyed", "FieldValue(field=an_int, column='three')")[0]
    assert (r.value, r.count) == (33, 1)


def test_unionrows_count(keyed):
    r = keyed.executor.execute(
        "keyed", "Count(UnionRows(Rows(field=an_id_set)))")[0]
    assert int(r) == 4


def test_options_shards():
    eng = _engine([
        "CREATE TABLE unkeyed (_id id, an_id_set idset)",
        f"INSERT INTO unkeyed (_id, an_id_set) VALUES (1, (1, 2)), "
        f"({W + 2}, (1, 3))",
    ])
    # shard 0 only: the shard-1 record's bit is out of scope
    r = eng.executor.execute(
        "unkeyed", "Options(Count(Row(an_id_set=1)), shards=[0])")[0]
    assert int(r) == 1


def test_distinct_cross_index_join():
    eng = _engine([
        "CREATE TABLE users (_id id, name string, age int)",
        "INSERT INTO users (_id, name, age) VALUES (0, 'a', 21), "
        "(1, 'b', 18), (2, 'c', 28), (3, 'd', 34), (4, 'e', 36)",
        "CREATE TABLE orders (_id id, userid int, price decimal(2))",
        "INSERT INTO orders (_id, userid, price) VALUES "
        "(0, 1, 9.99), (1, 0, 3.99), (2, 2, 14.99), (3, 3, 5.99), "
        "(4, 1, 12.99), (5, 2, 1.99)",
    ])
    r = eng.executor.execute(
        "users",
        "Intersect(Distinct(Row(price > 10), index=orders, "
        "field=userid))")[0]
    assert sorted(int(c) for c in r.columns()) == [1, 2]
