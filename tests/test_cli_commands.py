"""Spawn the REAL `pilosa-tpu` CLI as a subprocess — the operator
surface (cmd/root.go analog).  The in-process suites never execute
cmd_server/cmd_dax, which let a startup crash (a nonexistent logger
import) ship unnoticed in round 4."""

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest


def _free_port() -> int:
    """Probe a free TCP port instead of hardcoding one — a fixed port
    races against parallel suites and anything already listening."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(args, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {}))
    return subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.cli.main", *args],
        env=env, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))


def _req(port, method, path, body=None, timeout=180):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    c.request(method, path, body=body)
    out = json.loads(c.getresponse().read())
    c.close()
    return out


def test_server_command_serves_sql(tmp_path):
    port = _free_port()
    p = _spawn(["server", "--data-dir", str(tmp_path),
                "--port", str(port), "--grpc-port", "-1"])
    try:
        deadline = time.time() + 120
        while True:
            try:
                st = _req(port, "GET", "/status", timeout=5)
                break
            except OSError:
                if time.time() > deadline:
                    err = p.stderr.read() if p.poll() is not None \
                        else "(still starting)"
                    pytest.fail(f"server never listened: {err[-500:]}")
                time.sleep(0.5)
        assert st["state"] == "NORMAL"
        _req(port, "POST", "/sql",
             "CREATE TABLE t (_id id, n int min 0 max 100)")
        _req(port, "POST", "/sql",
             "INSERT INTO t VALUES (1, 5), (2, 9)")
        out = _req(port, "POST", "/sql", "SELECT sum(n) FROM t")
        assert out["data"] == [[14]]
    finally:
        p.send_signal(signal.SIGINT)
        try:
            p.wait(timeout=60)
        except subprocess.TimeoutExpired:
            p.kill()
