"""Serving-path tests — cross-query dispatch coalescing
(executor/serving.py): micro-batcher fusion, the versioned result
cache, and consistency under concurrent writes.

Correctness bar (ISSUE 2): batched and cached execution is bit-exact
vs per-query execution, a write to a referenced fragment evicts
exactly the affected cache entries, and a query admitted before a
write sees a consistent fragment-version snapshot or is re-executed.
"""

import random
import threading

import pytest

from pilosa_tpu.api import serialize_result
from pilosa_tpu.executor.executor import Executor
from pilosa_tpu.executor.serving import (
    ResultCache,
    Uncacheable,
    field_snapshot,
    query_fields,
)
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.models.schema import FieldOptions, FieldType
from pilosa_tpu.obs import metrics
from pilosa_tpu.pql import parse


def build_holder(track_existence: bool = True) -> Holder:
    h = Holder()
    idx = h.create_index("i", track_existence=track_existence)
    idx.create_field("a")
    idx.create_field("b")
    idx.create_field("v", FieldOptions(type=FieldType.INT,
                                       min=0, max=1000))
    ex = Executor(h)
    for c in range(300):
        ex.execute("i", f"Set({c}, a={c % 4})")
        ex.execute("i", f"Set({c}, b={c % 6})")
        ex.execute("i", f"Set({c}, v={(c * 7) % 97})")
    return h


@pytest.fixture(scope="module")
def holder():
    return build_holder()


QUERIES = [
    "Count(Row(a=1))",
    "Count(Intersect(Row(a=1), Row(b=2)))",
    "Count(Union(Row(a=0), Row(b=5)))",
    "Count(Difference(Row(a=2), Row(b=1)))",
    "Count(Xor(Row(a=3), Row(b=0)))",
    "Count(Not(Row(a=1)))",
    "Count(Row(v > 50))",
    "Count(Row(v >= 12))",
    "Count(Row(v == 14))",
    "Row(a=2)",
    "Union(Row(a=1), Row(b=3))",
    "Intersect(Row(a=1), Row(v < 40))",
    "TopN(a, n=3)",
    "TopN(a, Row(b=1), n=2)",
    "TopK(b, k=4)",
    "Sum(Row(a=1), field=v)",
    "Sum(field=v)",
    "All()",
]


def results_of(ex, q, serving=False):
    fn = ex.execute_serving if serving else ex.execute
    return [serialize_result(r) for r in fn("i", q)]


def test_solo_bit_exact(holder):
    """Every query through the serving path (cache cold AND warm)
    matches per-query execution exactly."""
    plain = Executor(holder)
    srv = Executor(holder)
    srv.enable_serving(window_s=0.0, max_batch=8)
    for q in QUERIES:
        want = results_of(plain, q)
        assert results_of(srv, q, serving=True) == want, q   # cold
        assert results_of(srv, q, serving=True) == want, q   # cached


def test_concurrent_batched_bit_exact(holder):
    """N concurrent distinct queries fuse into shared dispatches and
    every one demuxes to its own exact result."""
    plain = Executor(holder)
    srv = Executor(holder)
    layer = srv.enable_serving(window_s=0.05, max_batch=64,
                               cache_bytes=0)  # no cache: force fusion
    want = {q: results_of(plain, q) for q in QUERIES}
    batches_before = metrics.SERVING_BATCH_SIZE.count()
    got = {}
    lock = threading.Lock()
    barrier = threading.Barrier(len(QUERIES))

    def run(q):
        barrier.wait()
        r = results_of(srv, q, serving=True)
        with lock:
            got[q] = r

    threads = [threading.Thread(target=run, args=(q,)) for q in QUERIES]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert got == want
    # coalescing actually happened: fewer batches than queries
    assert metrics.SERVING_BATCH_SIZE.count() - batches_before \
        < len(QUERIES)
    assert layer.cache is None


def test_cache_hit_skips_execution(holder):
    srv = Executor(holder)
    layer = srv.enable_serving(window_s=0.0, max_batch=8)
    q = "Count(Intersect(Row(a=1), Row(b=2)))"
    first = results_of(srv, q, serving=True)
    h0 = layer.cache.hits
    assert results_of(srv, q, serving=True) == first
    assert layer.cache.hits == h0 + 1


def test_write_invalidates_exactly():
    """Acceptance-pinned: a write to a referenced fragment evicts
    exactly the entries that read it — other entries stay hot."""
    h = build_holder(track_existence=False)
    srv = Executor(h)
    layer = srv.enable_serving(window_s=0.0, max_batch=8)
    plain = Executor(h)
    srv.execute_serving("i", "Count(Row(a=1))")
    srv.execute_serving("i", "Count(Row(b=1))")
    srv.execute_serving("i", "Sum(field=v)")
    assert len(layer.cache) == 3
    # write touches field a only (no existence field on this index)
    srv.execute_serving("i", "Set(5000, a=1)")
    keys = {k[1] for k in layer.cache._entries}
    assert keys == {"[Count(Row(b=1))]", "[Sum(_field='v')]"}
    # the evicted entry recomputes correctly, the survivors still hit
    assert results_of(srv, "Count(Row(a=1))", serving=True) == \
        results_of(plain, "Count(Row(a=1))")
    h0 = layer.cache.hits
    srv.execute_serving("i", "Count(Row(b=1))")
    assert layer.cache.hits == h0 + 1


def test_cache_misses_after_field_drop_and_recreate():
    """Staleness must survive delete+recreate: fragment generation
    stamps (not reusable id()s) key the snapshot."""
    h = build_holder(track_existence=False)
    srv = Executor(h)
    layer = srv.enable_serving(window_s=0.0, max_batch=8)
    q = "Count(Row(a=1))"
    before = results_of(srv, q, serving=True)
    assert before[0] > 0
    idx = h.index("i")
    idx.delete_field("a")
    idx.create_field("a")
    ex2 = Executor(h)
    ex2.execute("i", "Set(1, a=1)")
    got = results_of(srv, q, serving=True)
    assert got == [1] != before
    assert layer.cache.misses >= 2


def test_cache_lazy_invalidation_on_direct_write(holder):
    """Writes that bypass the serving layer (imports, direct
    Executor.execute) still invalidate via the version guard."""
    h = build_holder(track_existence=False)
    srv = Executor(h)
    layer = srv.enable_serving(window_s=0.0, max_batch=8)
    q = "Count(Row(a=1))"
    before = results_of(srv, q, serving=True)
    Executor(h).execute("i", "Set(6000, a=1)")   # not via serving
    after = results_of(srv, q, serving=True)
    assert after[0] == before[0] + 1
    assert layer.cache.misses >= 2


def test_uncacheable_and_dep_walk(holder):
    idx = holder.index("i")
    fields = query_fields(idx, parse("Count(Intersect(Row(a=1), "
                                     "Row(v > 3)))"))
    assert {"a", "v"} <= set(fields)
    # Not() reads the existence field
    fields = query_fields(idx, parse("Count(Not(Row(a=1)))"))
    assert "_exists" in fields
    with pytest.raises(Uncacheable):
        query_fields(idx, parse("Options(Row(a=1), shards=[0])"))


def test_result_cache_lru_accounting():
    c = ResultCache(max_bytes=1 << 10)
    import numpy as np
    from pilosa_tpu.executor.results import RowResult
    h = build_holder(track_existence=False)
    idx = h.index("i")
    snap = field_snapshot(idx, frozenset(["a"]))
    for i in range(64):
        r = RowResult(idx.width)
        r.segments[0] = np.zeros(16, dtype=np.uint32)
        c.put(("i", f"q{i}", None), frozenset(["a"]), snap, [r])
    assert c.nbytes <= c.max_bytes
    assert c.nbytes == sum(e[3] for e in c._entries.values())


def _worker_counts(srv, n_iters, out, errs):
    try:
        prev = -1
        for _ in range(n_iters):
            (n,) = srv.execute_serving("i", "Count(Row(a=9))")
            # writes only ADD bits to row 9, so any version-consistent
            # sequence of counts is non-decreasing; a torn or stale
            # read would break monotonicity
            assert n >= prev, (n, prev)
            prev = n
            out.append(n)
    except Exception as e:  # pragma: no cover - failure reporting
        errs.append(e)


def test_stress_concurrent_reads_and_writes():
    """Satellite: hammer Executor.execute_serving from N threads while
    a writer interleaves Sets; assert version-consistent (monotone)
    results and intact cache accounting afterwards."""
    h = build_holder(track_existence=False)
    srv = Executor(h)
    layer = srv.enable_serving(window_s=0.0005, max_batch=16)
    writer_ex = Executor(h)
    n_writes, n_readers, n_iters = 120, 6, 40
    errs: list = []
    outs = [[] for _ in range(n_readers)]

    def writer():
        try:
            for c in range(n_writes):
                writer_ex.execute("i", f"Set({c}, a=9)")
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=writer)] + [
        threading.Thread(target=_worker_counts,
                         args=(srv, n_iters, outs[i], errs))
        for i in range(n_readers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    # final state exact
    (n,) = Executor(h).execute("i", "Count(Row(a=9))")
    assert n == n_writes
    # every reader converged to a value <= final, monotonically
    for o in outs:
        assert o == sorted(o)
        assert 0 <= o[-1] <= n_writes
    # cache accounting intact: no lost bytes, no over-budget pinning
    # (max_bytes None defers to the process device-memory ledger)
    eng = srv.stacked
    assert eng.cache.nbytes <= eng.cache._budget_cap()
    with eng.cache._lock:
        assert eng.cache.nbytes == sum(
            e[2] for e in eng.cache._entries.values())
    rc = layer.cache
    with rc._lock:
        assert rc.nbytes == sum(e[3] for e in rc._entries.values())
    assert rc.nbytes <= rc.max_bytes
    from pilosa_tpu.executor import stacked as stk
    assert len(stk._JIT_CACHE) <= stk._JIT_CACHE_MAX


def test_property_random_trees_with_writes():
    """Seeded random bitmap/aggregate trees: serving (batched + cached)
    vs per-query execution stays bit-exact across interleaved
    writes."""
    rng = random.Random(42)
    h = build_holder()
    plain = Executor(h)
    srv = Executor(h)
    srv.enable_serving(window_s=0.0, max_batch=8)

    def tree(depth):
        if depth <= 0 or rng.random() < 0.4:
            f, r = rng.choice([("a", rng.randrange(4)),
                               ("b", rng.randrange(6))])
            if rng.random() < 0.25:
                op = rng.choice([">", "<", ">=", "<=", "=="])
                return f"Row(v {op} {rng.randrange(97)})"
            return f"Row({f}={r})"
        op = rng.choice(["Union", "Intersect", "Difference", "Xor"])
        kids = ", ".join(tree(depth - 1)
                         for _ in range(rng.randrange(2, 4)))
        return f"{op}({kids})"

    def query():
        t = tree(2)
        wrap = rng.randrange(4)
        if wrap == 0:
            return f"Count({t})"
        if wrap == 1:
            return f"TopN(a, {t}, n=3)"
        if wrap == 2:
            return f"Sum({t}, field=v)"
        return t

    for round_ in range(6):
        for _ in range(12):
            q = query()
            want = results_of(plain, q)
            assert results_of(srv, q, serving=True) == want, q
            assert results_of(srv, q, serving=True) == want, q
        # interleave writes (through serving: sweeps the cache)
        for _ in range(5):
            c = rng.randrange(400)
            f, r = rng.choice([("a", rng.randrange(4)),
                               ("b", rng.randrange(6))])
            srv.execute_serving("i", f"Set({c}, {f}={r})")


def test_metrics_endpoint_exports_serving_histograms():
    """Satellite: p50/p95/p99 latency + batch occupancy reach the
    existing /metrics endpoint."""
    import http.client

    from pilosa_tpu.server import Server

    with Server() as s:
        s.start()
        c = http.client.HTTPConnection("127.0.0.1", s.port, timeout=10)
        c.request("POST", "/index/m1", body="{}")
        c.getresponse().read()
        c.request("POST", "/index/m1/field/f", body="{}")
        c.getresponse().read()
        import json as _json
        for q in ("Set(1, f=1)", "Count(Row(f=1))", "Count(Row(f=1))"):
            c.request("POST", "/index/m1/query",
                      body=_json.dumps({"query": q}),
                      headers={"Content-Type": "application/json"})
            r = c.getresponse()
            assert r.status == 200, r.read()
            r.read()
        c.request("GET", "/metrics")
        text = c.getresponse().read().decode()
        c.close()
    for needle in ("pilosa_serving_latency_seconds_p50",
                   "pilosa_serving_latency_seconds_p95",
                   "pilosa_serving_latency_seconds_p99",
                   "pilosa_serving_batch_size",
                   "pilosa_result_cache_total"):
        assert needle in text, needle


def test_http_server_serving_enabled_by_default():
    from pilosa_tpu.server import Server

    with Server() as s:
        assert s.api.executor.serving is not None
        assert s.api.executor.serving.cache is not None
