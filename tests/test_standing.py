"""Standing queries (executor/standing.py): write-through maintained
results on the fused serving plane.

The contract under test: a registered Count/TopN/GroupBy/SQL result
is BIT-EXACT against cold execution at every poll, stays on the
O(delta) incremental path for plain set/clear traffic, and declares
exactly one full-re-seed fallback per structural event (TTL quantum
expiry, rollup fold, delta-log overflow).  The kill switch
(PILOSA_TPU_STANDING=0) restores untouched sweep-on-write serving.
"""

import datetime as dt

import numpy as np
import pytest

from pilosa_tpu.executor.executor import Executor
from pilosa_tpu.executor.serving import _MISS
from pilosa_tpu.executor.standing import StandingUnsupported
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.models.schema import (
    FieldOptions,
    FieldType,
    TimeQuantum,
)


def build(n=300):
    # small shards (test_timeq idiom): the maintenance math is
    # width-independent and the tier-1 budget is not
    h = Holder(width=1 << 12)
    idx = h.create_index("i")
    idx.create_field("a", FieldOptions(type=FieldType.SET,
                                       cache_type="none"))
    idx.create_field("b")
    ex = Executor(h)
    for c in range(n):
        ex.execute("i", f"Set({c}, a={c % 4})")
        ex.execute("i", f"Set({c}, b={c % 6})")
    srv = ex.enable_serving(window_s=0.0, max_batch=8)
    return h, ex, srv


def test_count_incremental_bit_exact():
    h, ex, srv = build()
    q = "Count(Row(a=1))"
    srv.standing.register("i", q)
    cold_ex = Executor(h)
    # columns inside the seeded shard: a write to a virgin shard
    # creates fragments (structural), these stay purely incremental
    for w in ["Set(3001, a=1)", "Set(3002, a=1)", "Clear(1, a=1)",
              "Set(3001, a=1)", "Clear(3002, a=1)"]:
        ex.execute_serving("i", w)
        assert ex.execute_serving("i", q) == cold_ex.execute("i", q)
    (sq,) = srv.standing._by_id.values()
    assert sq.stats["fallback"] == 0
    assert sq.stats["incremental"] >= 4  # idempotent replays may noop


def test_property_interleaved_all_kinds():
    """Seeded property suite: randomized interleaved writes vs
    standing Count/TopN/GroupBy, bit-exact vs cold at every poll."""
    h, ex, srv = build(n=160)
    rng = np.random.default_rng(0xC0FFEE)
    qs = [
        "Count(Row(a=1))",
        "Count(Union(Row(a=0), Row(b=5)))",
        "Count(Not(Row(a=2)))",
        "TopN(a, n=3)",
        "TopN(a, Row(b=1), n=2)",
        "GroupBy(Rows(a), Rows(b))",
    ]
    for q in qs:
        srv.standing.register("i", q)
    cold_ex = Executor(h)
    for step in range(40):
        col = int(rng.integers(0, 400))
        row = int(rng.integers(0, 6))
        fld = "a" if rng.integers(0, 2) else "b"
        op = "Clear" if rng.integers(0, 3) == 0 else "Set"
        rid = row % 4 if fld == "a" else row
        ex.execute_serving("i", f"{op}({col}, {fld}={rid})")
        if step % 4 == 0:
            for q in qs:
                assert (ex.execute_serving("i", q)
                        == cold_ex.execute("i", q)), (step, q)
    # quiesce: every registration still bit-exact, all maintained
    for q in qs:
        assert ex.execute_serving("i", q) == cold_ex.execute("i", q)
    for sq in srv.standing._by_id.values():
        assert sq.stats["incremental"] > 0, sq.describe()
        assert sq.stats["fallback"] == 0, sq.describe()


def test_sql_standing_bit_exact():
    from pilosa_tpu.sql.engine import SQLEngine
    h, ex, srv = build()
    eng = SQLEngine(h, ex)
    s = "SELECT COUNT(*) FROM i WHERE a = 1"
    srv.standing.register_sql(eng, s)
    cold = SQLEngine(h, Executor(h))
    for w in ["INSERT INTO i (_id, a) VALUES (9001, 1)",
              "INSERT INTO i (_id, b) VALUES (9002, 2)",
              "DELETE FROM i WHERE _id = 9001"]:
        eng.query_one(w)
        got, want = eng.query_one(s), cold.query_one(s)
        assert got.rows == want.rows and got.schema == want.schema
    (sq,) = srv.standing._by_id.values()
    assert sq.kind == "sql" and sq.stats["incremental"] > 0


def test_unsupported_shapes_reject_typed():
    h, ex, srv = build()
    h.index("i").create_field("v", FieldOptions(
        type=FieldType.INT, min=0, max=100))
    for bad in ["Count(Row(v > 3))", "Sum(field=v)", "TopK(b, k=4)",
                "GroupBy(Rows(a), aggregate=Count(Distinct(field=b)))",
                "Row(a=1)"]:
        with pytest.raises(StandingUnsupported):
            srv.standing.register("i", bad)
    # unfiltered TopN over a rank-cached field would have to match
    # the cold path's APPROXIMATE cache merge — rejected
    with pytest.raises(StandingUnsupported):
        srv.standing.register("i", "TopN(b, n=3)")
    assert srv.standing.list_info() == []


def test_ttl_expiry_rescopes_standing_cover():
    """Regression (ISSUE 18 satellite): a TTL-expired quantum under
    a standing registration must re-scope the cover — ONE declared
    full re-evaluation — and never serve the retired gens."""
    h = Holder()
    idx = h.create_index("t", track_existence=False)
    f = idx.create_field("ev", FieldOptions(
        type=FieldType.TIME, time_quantum=TimeQuantum("YMD"),
        ttl=86400.0))
    old = dt.datetime(2021, 3, 1, 12)
    recent = dt.datetime.now(dt.timezone.utc).replace(tzinfo=None)
    f.set_bit(1, 10, timestamp=old)
    f.set_bit(1, 11, timestamp=old)
    f.set_bit(1, 20, timestamp=recent)
    ex = Executor(h)
    srv = ex.enable_serving(window_s=0.0, max_batch=8)
    q = ("Count(Row(ev=1, from='2021-01-01T00:00',"
         " to='2030-01-01T00:00'))")
    srv.standing.register("t", q)
    assert ex.execute_serving("t", q) == [3]
    removed = h.remove_expired_views()
    assert any(v.startswith("standard_2021") for v in removed)
    srv.standing.on_write()  # the server maintenance tick's notify
    # only the recent bit survives the expired quantum — maintained
    # and cold agree, through exactly one declared fallback
    assert ex.execute_serving("t", q) == [1]
    assert ex.execute("t", q) == [1]
    (sq,) = srv.standing._by_id.values()
    assert sq.stats["fallback"] == 1


def test_rollup_fold_keeps_standing_bit_exact():
    """A [timeq] rollup fold (fine view OR-folded into its coarser
    parent) is a structural event: the cover re-scopes through one
    fallback and the maintained result stays bit-exact."""
    h = Holder()
    idx = h.create_index("t", track_existence=False)
    f = idx.create_field("ev", FieldOptions(
        type=FieldType.TIME, time_quantum=TimeQuantum("MD")))
    old = dt.datetime(2021, 3, 1, 12)
    for c in range(20):
        f.set_bit(1, c, timestamp=old)
    ex = Executor(h)
    srv = ex.enable_serving(window_s=0.0, max_batch=8)
    q = ("Count(Row(ev=1, from='2021-03-01T00:00',"
         " to='2021-03-02T00:00'))")
    srv.standing.register("t", q)
    assert ex.execute_serving("t", q) == [20]
    folded = f.rollup_views(now=dt.datetime(2022, 1, 1))
    assert folded  # day views folded into month views
    srv.standing.on_write()
    assert ex.execute_serving("t", q) == [20]
    assert ex.execute("t", q) == [20]


def test_delta_log_overflow_falls_back_once():
    """More landed mutations than the fragment delta log holds
    between polls: deltas_since() cannot prove coverage, so the
    registration declares ONE full re-seed — and stays exact."""
    from pilosa_tpu.models import fragment
    h, ex, srv = build()
    q = "Count(Row(a=1))"
    srv.standing.register("i", q)
    (sq,) = srv.standing._by_id.values()
    # land an over-log burst directly (bypassing the serving layer's
    # per-write push, like a bulk import would)
    idx = h.index("i")
    f = idx.field("a")
    for c in range(fragment.DELTA_LOG_MAX + 10):
        f.set_bit(1, 1000 + c)
    srv.standing.on_write("i", {"a"})
    cold_ex = Executor(h)
    assert ex.execute_serving("i", q) == cold_ex.execute("i", q)
    assert sq.stats["fallback"] == 1


def test_kill_switch_disables_plane(monkeypatch):
    h, ex, srv = build()
    q = "Count(Row(a=1))"
    srv.standing.register("i", q)
    monkeypatch.setenv("PILOSA_TPU_STANDING", "0")
    # registration rejects...
    with pytest.raises(StandingUnsupported):
        srv.standing.register("i", "Count(Row(a=2))")
    # ...the push and the pull both no-op...
    srv.standing.on_write("i", {"a"})
    assert srv.standing.catch_up(("i", "x", None)) is _MISS
    # ...and polls stay bit-exact through the normal swept path
    cold_ex = Executor(h)
    ex.execute_serving("i", "Set(7001, a=1)")
    assert ex.execute_serving("i", q) == cold_ex.execute("i", q)
    (sq,) = srv.standing._by_id.values()
    assert sq.stats["incremental"] == 0
    monkeypatch.delenv("PILOSA_TPU_STANDING")
    # re-enabled: the next landed write routes back through
    # maintenance and the registration catches up from its stale
    # snapshot (the disabled-era write arrives in the same diff)
    ex.execute_serving("i", "Set(3005, a=1)")
    assert ex.execute_serving("i", q) == cold_ex.execute("i", q)
    assert sq.stats["incremental"] + sq.stats["fallback"] > 0


def test_standing_entry_survives_sweeps_and_eviction():
    h, ex, srv = build()
    q = "Count(Row(a=1))"
    srv.standing.register("i", q)
    key = ("i", repr(__import__("pilosa_tpu.pql",
                                fromlist=["parse"]).parse(q).calls),
           None)
    assert key in srv.cache
    # a full sweep after a write must NOT evict the maintained entry
    ex.execute("i", "Set(8001, a=1)")  # solo write, no push
    srv.cache.sweep(h)
    assert key in srv.cache
    # stale get misses without dropping it; catch_up then serves
    cold_ex = Executor(h)
    assert ex.execute_serving("i", q) == cold_ex.execute("i", q)
    # reclaim pressure cannot evict it either
    assert srv.cache._reclaim(1 << 30) == 0
    assert key in srv.cache
    # unregister returns the key to normal lifecycle and drops it
    (sq,) = srv.standing._by_id.values()
    assert srv.standing.unregister(sq.sid)
    assert key not in srv.cache
    assert ex.execute_serving("i", q) == cold_ex.execute("i", q)


def test_registration_admission_limits():
    from pilosa_tpu.executor import standing as st
    h, ex, srv = build()
    st.configure(max_registrations=2)
    try:
        srv.standing.register("i", "Count(Row(a=1))")
        srv.standing.register("i", "Count(Row(a=2))")
        with pytest.raises(StandingUnsupported):
            srv.standing.register("i", "Count(Row(a=3))")
        # duplicate registration of a live key rejects too
        st.configure(max_registrations=256)
        with pytest.raises(StandingUnsupported):
            srv.standing.register("i", "Count(Row(a=1))")
    finally:
        st.configure(max_registrations=256)


def test_http_standing_surface():
    import json
    import urllib.request

    from pilosa_tpu.server.http import Server

    h = Holder(width=1 << 12)
    idx = h.create_index("i")
    idx.create_field("a", FieldOptions(type=FieldType.SET,
                                       cache_type="none"))
    ex = Executor(h)
    for c in range(50):
        ex.execute("i", f"Set({c}, a={c % 3})")
    srv_http = Server(h, port=0).start()
    try:
        base = f"http://127.0.0.1:{srv_http.port}"

        def call(method, path, body=None):
            data = (json.dumps(body).encode()
                    if body is not None else None)
            req = urllib.request.Request(base + path, data=data,
                                         method=method)
            with urllib.request.urlopen(req) as r:
                return json.loads(r.read() or b"{}")

        out = call("POST", "/index/i/standing",
                   {"query": "Count(Row(a=1))"})
        assert out["kind"] == "count" and out["id"] == 1
        out = call("POST", "/index/i/standing",
                   {"sql": "SELECT COUNT(*) FROM i"})
        assert out["kind"] == "sql"
        listed = call("GET", "/standing")["standing"]
        assert [e["id"] for e in listed] == [1, 2]
        dbg = call("GET", "/debug/standing")
        assert dbg["enabled"] and len(dbg["standing"]) == 2
        # writes through the HTTP query surface maintain; poll serves
        call("POST", "/index/i/query", {"query": "Set(9001, a=1)"})
        got = call("POST", "/index/i/query",
                   {"query": "Count(Row(a=1))"})
        want = Executor(h).execute("i", "Count(Row(a=1))")
        assert got["results"] == want
        assert call("DELETE", "/standing/1") == {"removed": 1}
        assert [e["id"] for e in call("GET", "/standing")["standing"]
                ] == [2]
        # unsupported shape is a typed 400
        try:
            call("POST", "/index/i/standing",
                 {"query": "Sum(field=a)"})
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        srv_http.close()
