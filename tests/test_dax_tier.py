"""Disaggregated DAX tier tests (ISSUE 20): blob shard store,
stateless budget-paged workers, SLO-driven autoscaling.

The property the whole suite pins: a worker booted with an EMPTY data
dir, hydrating from blob manifests through a ledger 10x smaller than
the corpus, answers every query bit-exact vs a local-disk node — and
keeps doing so across scale-out, scale-in, and every drill in the
fault matrix (blob-unavailable, blob-torn-upload,
worker-hydrate-crash, scale-event-interrupted).
"""

import json
import os
import urllib.request

import pytest

from pilosa_tpu.dax import settings
from pilosa_tpu.dax.server import DAXService
from pilosa_tpu.dax.writelogger import WriteLogger
from pilosa_tpu.obs import faults, incidents
from pilosa_tpu.storage.blob import (
    BlobError,
    BlobStore,
    LocalDirBackend,
    MemBackend,
    make_backend,
)

SHARD = 1 << 20

SCHEMA = {"indexes": [{"name": "t", "fields": [
    {"name": "f", "options": {"type": "set"}},
    {"name": "v", "options": {"type": "int", "min": 0, "max": 1000}},
]}]}

# 24 shards: jump-hash actually splits table "t" across two workers
# (with <=8 shards every one happens to land in bucket 0 of 2)
N = 24

_SIG = {"burn": 9.9, "pressure": {}, "shed": 0, "shed_delta": 0.0}


@pytest.fixture(autouse=True)
def _tier_env(monkeypatch):
    """Deterministic knobs per test — via the env twins, because
    every Server construction re-applies its config's [dax] stanza
    over settings.configure() state.  Restore module state after."""
    monkeypatch.setenv("PILOSA_TPU_DAX_PREFETCH", "0")
    monkeypatch.setenv("PILOSA_TPU_DAX_COOLDOWN_S", "0")
    monkeypatch.setenv("PILOSA_TPU_DAX_CHASE_LAG", "2")
    monkeypatch.setenv("PILOSA_TPU_DAX_CHASE_ROUNDS", "4")
    saved = {k: getattr(settings, k) for k in vars(settings)
             if k.startswith("_") and not k.startswith("__")
             and not callable(getattr(settings, k))}
    yield
    faults.clear()
    for k, v in saved.items():
        setattr(settings, k, v)


def _seed(svc, n_shards=N):
    svc.queryer.apply_schema(SCHEMA)
    cols = [s * SHARD + 7 for s in range(n_shards)]
    svc.queryer.import_bits("t", "f", [1] * n_shards, cols)
    svc.queryer.import_values("t", "v", cols,
                              [(s % 90) + 10 for s in range(n_shards)])
    return cols


def _checkpoint(svc):
    """Push every held shard's state into the blob tier."""
    for w in svc.workers:
        for t, shards in list(w.held.items()):
            for s in sorted(shards):
                w.snapshot_shard(t, s)


def _seal(svc):
    for w in svc.workers:
        for t, shards in list(w.held.items()):
            for s in sorted(shards):
                w.hyd.seal_tail(t, s)


def _results(svc):
    return {
        "row1": svc.queryer.query("t", "Row(f=1)")
        ["results"][0]["columns"],
        "row2": svc.queryer.query("t", "Row(f=2)")
        ["results"][0]["columns"],
        "cnt": svc.queryer.query("t", "Count(Row(f=1))")["results"],
        "sum": svc.queryer.query("t", "Sum(Row(f=1), field=v)")
        ["results"][0],
    }


def _cold_service(tmp_path, name, blob, budget=None):
    """A fresh service whose ONLY worker boots with an empty private
    data dir — everything it serves must come from the blob tier."""
    svc = DAXService(str(tmp_path / name), n_workers=0, blob=blob)
    svc.queryer.apply_schema(SCHEMA)
    svc.add_blob_worker(f"{name}-w0", budget_bytes=budget)
    for t, s in blob.shards():
        svc.controller.add_shards(t, [s])
    return svc


# ---------------------------------------------------------------------------
# blob store
# ---------------------------------------------------------------------------

@pytest.fixture(params=["mem", "dir"])
def backend(request, tmp_path):
    if request.param == "mem":
        return MemBackend()
    return LocalDirBackend(str(tmp_path / "blob"))


def test_blob_store_roundtrip(backend):
    store = BlobStore(backend)
    assert store.manifest("t", 0) is None
    assert store.covered_version("t", 0) == 0
    assert store.restore("t", 0) is None

    store.put_snapshot("t", 0, 5, b"snapshot-at-5")
    assert store.covered_version("t", 0) == 5
    store.put_segment("t", 0, 5, 8, b"entries-6-7-8")
    assert store.covered_version("t", 0) == 8
    # gapped seal rejected: the manifest never claims coverage it
    # doesn't have
    with pytest.raises(BlobError, match="gap"):
        store.put_segment("t", 0, 9, 12, b"gap")
    with pytest.raises(BlobError, match="empty"):
        store.put_segment("t", 0, 8, 8, b"")
    # stale snapshot (older than the manifest's) rejected
    with pytest.raises(BlobError, match="stale"):
        store.put_snapshot("t", 0, 4, b"old")

    version, snap, segs = store.restore("t", 0)
    assert (version, snap) == (8, b"snapshot-at-5")
    assert segs == [(5, 8, b"entries-6-7-8")]

    # a newer snapshot retires the segments it supersedes
    store.put_snapshot("t", 0, 8, b"snapshot-at-8")
    version, snap, segs = store.restore("t", 0)
    assert (version, snap, segs) == (8, b"snapshot-at-8", [])

    store.put_snapshot("t", 1, 2, b"other-shard")
    assert store.shards() == [("t", 0), ("t", 1)]
    store.delete_shard("t", 0)
    assert store.shards() == [("t", 1)]
    assert store.manifest("t", 0) is None


def test_blob_torn_upload_never_visible(backend):
    """An upload that dies after the data put but before the manifest
    flip leaves the OLD manifest resolving old, complete objects."""
    store = BlobStore(backend)
    store.put_snapshot("t", 3, 10, b"good-snapshot-v10")
    faults.inject("blob-torn-upload", times=1)
    with pytest.raises(faults.InjectedFault):
        store.put_snapshot("t", 3, 20, b"newer-snapshot-v20")
    # reader sees the v10 world, checksum-intact
    version, snap, segs = store.restore("t", 3)
    assert (version, snap, segs) == (10, b"good-snapshot-v10", [])
    assert store.covered_version("t", 3) == 10
    # the retry (fault exhausted) completes the flip
    store.put_snapshot("t", 3, 20, b"newer-snapshot-v20")
    assert store.restore("t", 3)[:2] == (20, b"newer-snapshot-v20")


def test_blob_checksum_mismatch_is_typed(tmp_path):
    store = BlobStore(LocalDirBackend(str(tmp_path / "b")))
    store.put_snapshot("t", 0, 1, b"the-real-bytes")
    key = store.manifest("t", 0)["snapshot"]["key"]
    # corrupt the object in place (bypassing the put path)
    with open(os.path.join(str(tmp_path / "b"),
                           *key.split("/")), "wb") as f:
        f.write(b"bitrot")
    with pytest.raises(BlobError, match="checksum mismatch"):
        store.restore("t", 0)


def test_localdir_backend_hygiene(tmp_path):
    b = LocalDirBackend(str(tmp_path / "b"))
    for bad in ("/etc/passwd", "~/x", "a/../../escape"):
        with pytest.raises(BlobError, match="invalid object key"):
            b.put(bad, b"x")
    b.put("t/00000/obj", b"data")
    # torn-put debris (.tmp) is never listable
    with open(str(tmp_path / "b" / "t" / "00000" / "half.tmp"),
              "wb") as f:
        f.write(b"partial")
    assert b.list() == ["t/00000/obj"]
    with pytest.raises(BlobError, match="no such object"):
        b.get("t/00000/missing")
    with pytest.raises(BlobError):
        make_backend("dir", None)
    with pytest.raises(BlobError):
        make_backend("s3", "/x")


def test_blob_unavailable_fault_is_typed(backend):
    from pilosa_tpu.storage.blob import BlobUnavailableError
    store = BlobStore(backend)
    store.put_snapshot("t", 0, 1, b"x")
    faults.inject("blob-unavailable", times=1)
    with pytest.raises(BlobUnavailableError):
        store.manifest("t", 0)
    assert store.covered_version("t", 0) == 1  # recovered


# ---------------------------------------------------------------------------
# stateless workers: cold start, paging, warming
# ---------------------------------------------------------------------------

def test_cold_start_bit_exact_10x_over_budget(tmp_path):
    """The tentpole property: an empty-data-dir worker hydrating
    snapshot+segments from blob through a ledger >=10x smaller than
    the corpus answers bit-exact vs the local-disk fleet, paging
    residency (evictions > 0, resident bytes never over budget)."""
    blob = BlobStore(MemBackend())
    src = DAXService(str(tmp_path / "src"), n_workers=2, blob=blob)
    cols = _seed(src)
    _checkpoint(src)                       # wave 1 -> snapshots
    src.queryer.import_bits("t", "f", [2] * N,
                            [c + 1 for c in cols])
    _seal(src)                             # wave 2 -> WAL segments
    oracle = _results(src)

    # probe: unbounded cold worker measures the corpus and doubles as
    # the blob-path bit-exactness check
    probe = _cold_service(tmp_path, "probe", blob)
    try:
        assert _results(probe) == oracle
        total = probe.workers[0].hyd.payload()["resident_bytes"]
    finally:
        probe.close()
    budget = max(total // 12, 64)
    assert total >= 10 * budget

    cold = _cold_service(tmp_path, "cold", blob, budget=budget)
    try:
        assert _results(cold) == oracle
        p = cold.workers[0].hyd.payload()
        assert p["resident_bytes"] <= budget
        assert p["evictions"] > 0
        assert p["hydrations"] > N  # re-hydration = paging happened
        assert p["pressure"] <= 1.0
    finally:
        cold.close()
        src.close()


def test_cold_worker_writes_continue_blob_numbering(tmp_path):
    """A write landing on a hydrated stateless worker appends to its
    PRIVATE log at the blob's absolute version — sealing afterwards
    extends the manifest instead of regressing it."""
    blob = BlobStore(MemBackend())
    src = DAXService(str(tmp_path / "src"), n_workers=1, blob=blob)
    _seed(src, n_shards=2)
    _checkpoint(src)
    covered0 = blob.covered_version("t", 0)
    assert covered0 > 0
    src.close()

    cold = _cold_service(tmp_path, "cold", blob)
    try:
        cold.queryer.import_bits("t", "f", [3], [7])
        w = cold.workers[0]
        assert w.wl.version("t", 0) == covered0 + 1
        assert w.hyd.seal_tail("t", 0) == 1
        assert blob.covered_version("t", 0) == covered0 + 1
        r = cold.queryer.query("t", "Row(f=3)")
        assert r["results"][0]["columns"] == [7]
    finally:
        cold.close()


def test_prefetch_warms_cold_shards(tmp_path, monkeypatch):
    """One touched shard kicks the warmer; the hottest still-cold
    assigned shards hydrate in the background."""
    import time
    blob = BlobStore(MemBackend())
    src = DAXService(str(tmp_path / "src"), n_workers=1, blob=blob)
    _seed(src, n_shards=6)
    _checkpoint(src)
    src.close()
    monkeypatch.setenv("PILOSA_TPU_DAX_PREFETCH", "3")
    cold = _cold_service(tmp_path, "cold", blob)
    try:
        w = cold.workers[0]
        from pilosa_tpu.cluster.client import InternalClient
        InternalClient()._request(
            w.uri, "POST", "/index/t/query",
            {"query": "Count(Row(f=1))", "shards": [0]})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and len(w.hyd._resident) < 1 + 3:
            time.sleep(0.02)
        assert len(w.hyd._resident) >= 1 + 3
    finally:
        cold.close()


def test_kill_switch_ab_bit_exact(tmp_path, monkeypatch):
    """PILOSA_TPU_DAX_BLOB=0 drops workers back to local-disk
    snapshot+log hydration; results match the blob path bit-exact."""
    blob = BlobStore(MemBackend())
    svc = DAXService(str(tmp_path / "svc"), n_workers=2, blob=blob)
    try:
        _seed(svc, n_shards=6)
        _checkpoint(svc)
        on = _results(svc)

        def evict_all():
            for w in svc.workers:
                for t, shards in list(w.held.items()):
                    for s in sorted(shards):
                        with w._lock:
                            w.hyd.release(t, s)
                            w.held[t].add(s)  # still assigned

        monkeypatch.setenv("PILOSA_TPU_DAX_BLOB", "0")
        assert not settings.blob_enabled()
        evict_all()
        assert _results(svc) == on      # local-disk arm
        monkeypatch.delenv("PILOSA_TPU_DAX_BLOB")
        assert settings.blob_enabled()
        evict_all()
        assert _results(svc) == on      # blob arm
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# fault matrix: hydration crash / blob outage
# ---------------------------------------------------------------------------

def test_blob_unavailable_query_typed_503(tmp_path):
    """Blob outage during cold hydration surfaces as a typed 503 on
    the query path — degraded, never a silent partial result — and
    clears with the outage."""
    from pilosa_tpu.cluster.client import RemoteError
    blob = BlobStore(MemBackend())
    src = DAXService(str(tmp_path / "src"), n_workers=1, blob=blob)
    _seed(src, n_shards=4)
    _checkpoint(src)
    oracle = _results(src)
    src.close()
    cold = _cold_service(tmp_path, "cold", blob)
    try:
        faults.inject("blob-unavailable", times=0)  # unlimited
        with pytest.raises(RemoteError) as ei:
            cold.queryer.query("t", "Count(Row(f=1))")
        assert ei.value.status == 503
        assert "blob tier unavailable" in str(ei.value)
        faults.clear("blob-unavailable")
        assert _results(cold) == oracle
    finally:
        cold.close()


def test_worker_hydrate_crash_leaves_shard_cold(tmp_path):
    """A crash mid-hydrate leaves NO partial residency: the query
    fails, the shard stays cold, the next touch hydrates clean."""
    from pilosa_tpu.cluster.client import RemoteError
    blob = BlobStore(MemBackend())
    src = DAXService(str(tmp_path / "src"), n_workers=1, blob=blob)
    _seed(src, n_shards=4)
    _checkpoint(src)
    oracle = _results(src)
    src.close()
    cold = _cold_service(tmp_path, "cold", blob)
    try:
        w = cold.workers[0]
        faults.inject("worker-hydrate-crash", times=1)
        with pytest.raises(RemoteError):
            cold.queryer.query("t", "Count(Row(f=1))")
        assert not w.hyd._resident        # nothing half-loaded
        assert _results(cold) == oracle   # retry succeeds
    finally:
        cold.close()


def test_query_for_unheld_shard_is_typed_409(tmp_path):
    """A read naming a shard the worker doesn't hold (a migration
    flip raced the queryer's routing) answers a typed 409 — never a
    silent empty partial computed over released fragments.  The
    queryer re-resolves ownership and retries on that signal, so
    front-door reads stay exact."""
    from pilosa_tpu.cluster.client import InternalClient, RemoteError
    blob = BlobStore(MemBackend())
    src = DAXService(str(tmp_path / "src"), n_workers=1, blob=blob)
    try:
        _seed(src, n_shards=4)
        w = src.workers[0]
        with pytest.raises(RemoteError) as ei:
            InternalClient()._request(
                w.uri, "POST", "/index/t/query",
                {"query": "Count(Row(f=1))", "shards": [2, 99]})
        assert ei.value.status == 409
        assert "does not hold" in str(ei.value)
        # held shards still answer; the front stays exact throughout
        assert src.queryer.query(
            "t", "Count(Row(f=1))")["results"] == [4]
    finally:
        src.close()


def test_directive_release_drains_inflight_readers(tmp_path):
    """A directive revoking a shard DRAINS registered in-flight reads
    before freeing the fragments (the rebalance plane's RELEASE
    discipline): an admitted read completes over intact data instead
    of racing the release into a torn answer.  New reads for the
    revoked shard 409 at entry meanwhile — `held` drops first."""
    import threading
    import time

    from pilosa_tpu.dax.directive import Directive
    blob = BlobStore(MemBackend())
    src = DAXService(str(tmp_path / "src"), n_workers=1, blob=blob)
    try:
        _seed(src, n_shards=4)
        w = src.workers[0]
        key = ("t", 2)
        with w._lock:  # register a reader like _post_query_hydrated
            w._shard_readers[key] = 1
        applied = threading.Event()

        def revoke():
            w.apply_directive(Directive(
                address=w.address, version=w.directive_version + 1,
                assignments={"t": [0, 1, 3]}))
            applied.set()

        th = threading.Thread(target=revoke, daemon=True)
        th.start()
        time.sleep(0.3)
        # the drain holds the release while the reader is registered:
        # no epoch bump, fragments intact — but held already dropped,
        # so a NEW read for the shard is refused at entry
        assert not applied.is_set()
        assert w._release_epoch.get(key, 0) == 0
        assert 2 not in w.held.get("t", set())
        with w._lock:  # the reader finishes: deregister + notify
            del w._shard_readers[key]
            w._readers_cv.notify_all()
        th.join(10)
        assert applied.is_set()
        assert w._release_epoch.get(key, 0) == 1
    finally:
        src.close()


def test_import_blob_outage_rejects_write_typed(tmp_path):
    """A write that can't hydrate its baseline is REJECTED 503 — not
    applied to a half-restored shard."""
    from pilosa_tpu.cluster.client import RemoteError
    blob = BlobStore(MemBackend())
    src = DAXService(str(tmp_path / "src"), n_workers=1, blob=blob)
    _seed(src, n_shards=2)
    _checkpoint(src)
    src.close()
    cold = _cold_service(tmp_path, "cold", blob)
    try:
        faults.inject("blob-unavailable", times=0)
        with pytest.raises(RemoteError) as ei:
            cold.queryer.import_bits("t", "f", [9], [3])
        assert ei.value.status == 503
        faults.clear("blob-unavailable")
        cold.queryer.import_bits("t", "f", [9], [3])
        r = cold.queryer.query("t", "Row(f=9)")
        assert r["results"][0]["columns"] == [3]
    finally:
        cold.close()


# ---------------------------------------------------------------------------
# autoscaling: live scale-out / scale-in, interruption drills
# ---------------------------------------------------------------------------

def _blob_fleet(tmp_path, standbys=1):
    blob = BlobStore(MemBackend())
    svc = DAXService(str(tmp_path / "fleet"), n_workers=0, blob=blob)
    svc.add_blob_worker("w0")
    for i in range(standbys):
        svc.add_standby(f"s{i}")
    return svc


def test_scale_out_then_in_storm_bit_exact(tmp_path):
    """Read/write storm across a full scale cycle: standby admitted
    live (shards migrate through COPY/CHASE/FENCE/flip), writes land
    mid-cycle, drain returns the worker to the pool — every query
    bit-exact vs a cold oracle, no leaked fences."""
    incidents.get().clear()
    svc = _blob_fleet(tmp_path)
    try:
        cols = _seed(svc)
        _checkpoint(svc)
        before = _results(svc)

        d = svc.controller._scale_out(dict(_SIG))
        assert d["outcome"] == "done"
        assert sorted(svc.controller.workers) == ["s0", "w0"]
        moved = [k for k, v in d["outcomes"].items() if v == "done"]
        assert len(moved) >= 5            # 24 shards actually split
        assert svc.controller._fences == {}
        assert _results(svc) == before

        # writes land on the NEW owners
        svc.queryer.import_bits("t", "f", [2] * N,
                                [c + 1 for c in cols])
        after_w = _results(svc)
        assert after_w["row2"] == [c + 1 for c in cols]

        d = svc.controller._scale_in(dict(_SIG))
        assert d["outcome"] == "done"
        assert sorted(svc.controller.workers) == ["w0"]
        assert "s0" in svc.controller.standbys
        assert svc.controller._fences == {}
        assert _results(svc) == after_w

        # the scale events left incident bundles with the move plans
        assert incidents.get().wait_idle(30)
        got = {b["trigger"]: b
               for b in incidents.get().payload()["incidents"]}
        assert {"dax-scale-out", "dax-scale-in"} <= set(got)
        out_bundle = incidents.get().fetch(got["dax-scale-out"]["id"])
        ctx = out_bundle["context"]
        assert ctx["admitted"] == "s0"
        assert ctx["plan"] and all(v in ("done", "noop")
                                   for v in ctx["outcomes"].values())
    finally:
        svc.close()


def test_interrupted_scale_out_resumes(tmp_path):
    """A migration killed mid-event leaves a resumable overlay: the
    next reconcile finishes exactly the remaining moves."""
    svc = _blob_fleet(tmp_path)
    try:
        _seed(svc)
        _checkpoint(svc)
        before = _results(svc)
        faults.inject("scale-event-interrupted", times=1)
        d = svc.controller._scale_out(dict(_SIG))
        assert d["outcome"] == "partial"
        assert svc.controller._fences == {}   # fence never leaks
        assert _results(svc) == before        # donor still serves

        d2 = svc.controller.reconcile_once()
        assert d2["action"] == "resume"
        assert all(v in ("done", "noop")
                   for v in d2["outcomes"].values())
        assert svc.controller._pending_moves_locked() == []
        assert _results(svc) == before
    finally:
        svc.close()


def test_interrupted_scale_in_resumes_drain(tmp_path):
    """A drain killed mid-event keeps the draining worker in the
    roster (still owning its unmigrated shards); the next reconcile
    resumes THE DRAIN rather than rebalancing back onto it."""
    svc = _blob_fleet(tmp_path)
    try:
        _seed(svc)
        _checkpoint(svc)
        assert svc.controller._scale_out(dict(_SIG))["outcome"] \
            == "done"
        before = _results(svc)

        faults.inject("scale-event-interrupted", times=1)
        d = svc.controller._scale_in(dict(_SIG))
        assert d["outcome"] == "partial"
        assert sorted(svc.controller.workers) == ["s0", "w0"]
        assert svc.controller._draining == "s0"
        assert _results(svc) == before

        d2 = svc.controller.reconcile_once()
        assert d2["action"] == "resume-drain"
        assert d2["outcome"] == "done"
        assert sorted(svc.controller.workers) == ["w0"]
        assert svc.controller._draining is None
        assert svc.controller._fences == {}
        assert _results(svc) == before
    finally:
        svc.close()


def test_reconcile_thresholds_drive_scaling(tmp_path, monkeypatch):
    """The reconcile loop's decisions follow the burn signal through
    the configured thresholds: high burn admits the standby, calm
    burn drains it, cooldown gates back-to-back events."""
    svc = _blob_fleet(tmp_path)
    try:
        _seed(svc, n_shards=8)
        _checkpoint(svc)
        burn = {"v": 0.0}
        monkeypatch.setattr(
            svc.controller, "signals",
            lambda: dict(_SIG, burn=burn["v"]))

        assert svc.controller.reconcile_once()["action"] == "none"
        burn["v"] = 5.0                   # > scale_out_burn (2.0)
        d = svc.controller.reconcile_once()
        assert d["action"] == "scale-out"
        assert sorted(svc.controller.workers) == ["s0", "w0"]

        monkeypatch.setenv("PILOSA_TPU_DAX_COOLDOWN_S", "3600")
        burn["v"] = 0.0                   # <= scale_in_burn
        assert svc.controller.reconcile_once()["action"] == "none"
        monkeypatch.setenv("PILOSA_TPU_DAX_COOLDOWN_S", "0")
        d = svc.controller.reconcile_once()
        assert d["action"] == "scale-in"
        assert sorted(svc.controller.workers) == ["w0"]
        assert svc.controller.last_reconcile["action"] == "scale-in"
    finally:
        svc.close()


def test_scale_state_survives_controller_restart(tmp_path):
    """Overlay pins, admitted list and a mid-drain marker persist in
    the schemar: a restarted controller resumes the interrupted
    event instead of forgetting it."""
    svc = _blob_fleet(tmp_path)
    try:
        _seed(svc)
        _checkpoint(svc)
        faults.inject("scale-event-interrupted", times=1)
        assert svc.controller._scale_out(dict(_SIG))["outcome"] \
            == "partial"
        pend = svc.controller._pending_moves_locked()
        assert pend

        svc.restart_controller()
        for w in svc.workers:  # re-register live workers
            svc.controller.register_worker(w.address, w.uri)
        assert svc.controller._pending_moves_locked() == pend
        d = svc.controller.reconcile_once()
        assert d["action"] == "resume"
        assert svc.controller._pending_moves_locked() == []
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# surfaces: /debug/dax, /dax/residency, metrics
# ---------------------------------------------------------------------------

def test_debug_dax_surface(tmp_path):
    blob = BlobStore(MemBackend())
    src = DAXService(str(tmp_path / "src"), n_workers=0, blob=blob)
    try:
        # unique address: /debug/dax lists every live hydrator in the
        # process, and prior tests' "worker0" may not be GC'd yet
        src.add_worker("dbg-w0")
        _seed(src, n_shards=4)
        _checkpoint(src)
        w = src.workers[0]
        with urllib.request.urlopen(
                f"http://{w.uri}/debug/dax", timeout=10) as r:
            body = json.loads(r.read())
        assert {"workers", "controllers"} <= set(body)
        mine = [p for p in body["workers"]
                if p["worker"] == w.address]
        assert mine and mine[0]["resident"]
        assert mine[0]["assigned"]["t"] == [0, 1, 2, 3]
        with urllib.request.urlopen(
                f"http://{w.uri}/dax/residency", timeout=10) as r:
            res = json.loads(r.read())
        assert res["worker"] == w.address
        assert res["blob"] is True
    finally:
        src.close()


def test_dax_metrics_move(tmp_path):
    from pilosa_tpu.obs import metrics
    blob = BlobStore(MemBackend())
    put0 = metrics.DAX_BLOB_BYTES.total(op="put")
    hyd0 = metrics.DAX_HYDRATIONS.total()
    src = DAXService(str(tmp_path / "src"), n_workers=1, blob=blob)
    try:
        _seed(src, n_shards=2)
        _checkpoint(src)
        assert metrics.DAX_BLOB_BYTES.total(op="put") > put0
        assert metrics.DAX_HYDRATIONS.total() > hyd0
        assert metrics.DAX_RESIDENT_SHARDS.value(
            worker=src.workers[0].address) == 2
    finally:
        src.close()


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------

def test_dax_config_stanzas_and_env_twins(tmp_path, monkeypatch):
    from pilosa_tpu import config as cfg
    c = cfg.load()
    assert (c.dax_blob, c.blob_backend, c.dax_max_workers) \
        == (True, "", 8)
    p = tmp_path / "server.toml"
    p.write_text("""
[blob]
backend = "dir"
root = "/data/blob"

[dax]
worker-budget-bytes = 4096
scale-out-burn = 3.5
max-workers = 4
lazy-hydrate = false
""")
    c = cfg.load(str(p))
    assert c.blob_backend == "dir"
    assert c.blob_root == "/data/blob"
    assert c.dax_worker_budget_bytes == 4096
    assert c.dax_scale_out_burn == 3.5
    assert c.dax_max_workers == 4
    assert c.dax_lazy_hydrate is False
    # env twins outrank the file
    monkeypatch.setenv("PILOSA_TPU_DAX_MAX_WORKERS", "6")
    assert cfg.load(str(p)).dax_max_workers == 6
    # apply pushes into the live settings module
    c.apply_dax_settings()
    assert settings.backend() == "dir"
    assert settings.worker_budget_bytes() == 4096
    assert settings.scale_out_burn() == 3.5
    assert not settings.lazy_hydrate()
    # ...whose accessors re-read their own env twins dynamically
    monkeypatch.setenv("PILOSA_TPU_DAX_SCALE_OUT_BURN", "7.25")
    assert settings.scale_out_burn() == 7.25


def test_kill_switch_outranks_config(monkeypatch):
    from pilosa_tpu import config as cfg
    monkeypatch.setenv("PILOSA_TPU_DAX_BLOB", "0")
    c = cfg.load()
    c.dax_blob = True
    c.apply_dax_settings()
    assert not settings.blob_enabled()
    monkeypatch.delenv("PILOSA_TPU_DAX_BLOB")
    assert settings.blob_enabled()


def test_generate_config_has_dax_stanzas():
    from pilosa_tpu.cli.main import DEFAULT_CONFIG
    assert "[dax]" in DEFAULT_CONFIG
    assert "[blob]" in DEFAULT_CONFIG
    assert "worker-budget-bytes" in DEFAULT_CONFIG
    assert "scale-out-burn" in DEFAULT_CONFIG


def test_blob_from_settings_respects_switch(tmp_path, monkeypatch):
    from pilosa_tpu.dax.server import blob_from_settings
    assert blob_from_settings(str(tmp_path)) is None  # no backend
    settings.configure(backend="dir", root="")
    b = blob_from_settings(str(tmp_path))
    assert b is not None
    assert b.backend.root == os.path.join(str(tmp_path), "blob")
    monkeypatch.setenv("PILOSA_TPU_DAX_BLOB", "0")
    assert blob_from_settings(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# writelogger fast-forward
# ---------------------------------------------------------------------------

def test_writelogger_fast_forward(tmp_path):
    wl = WriteLogger(str(tmp_path / "wl"))
    wl.append("t", 0, {"op": "bits", "rows": [1], "cols": [2]})
    wl.append("t", 0, {"op": "bits", "rows": [1], "cols": [3]})
    wl.fast_forward("t", 0, 10)
    assert wl.version("t", 0) == 10
    assert wl.replay("t", 0, from_version=0) == []
    v = wl.append("t", 0, {"op": "bits", "rows": [1], "cols": [4]})
    assert v == 11
    assert len(wl.replay("t", 0, from_version=10)) == 1
    wl.fast_forward("t", 0, 5)           # never regresses
    assert wl.version("t", 0) == 11
