"""Observability tests — logger, metrics exposition, tracing spans."""

import io
import threading

from pilosa_tpu.obs import (
    Logger,
    MetricsRegistry,
    NopTracer,
    RecordingTracer,
    set_tracer,
    start_span,
)
from pilosa_tpu.obs import logger as lg


def test_logger_levels_and_format():
    buf = io.StringIO()
    log = Logger(buf, level=lg.INFO)
    log.debug("hidden %d", 1)
    log.info("hello %s", "world")
    log.error("boom")
    out = buf.getvalue()
    assert "hidden" not in out
    assert "INFO" in out and "hello world" in out
    assert "ERROR" in out and "boom" in out


def test_logger_prefix():
    buf = io.StringIO()
    log = Logger(buf).with_prefix("executor")
    log.info("x")
    assert "[executor]" in buf.getvalue()


def test_counter_gauge_labels():
    r = MetricsRegistry()
    c = r.counter("q_total", "queries")
    c.inc()
    c.inc(2, index="i0")
    g = r.gauge("open_dbs")
    g.set(5)
    g.add(-1)
    text = r.render_text()
    assert "# TYPE q_total counter" in text
    assert "q_total 1" in text
    assert 'q_total{index="i0"} 2' in text
    assert "open_dbs 4" in text
    assert c.value(index="i0") == 2


def test_histogram_buckets():
    r = MetricsRegistry()
    h = r.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    text = r.render_text()
    assert 'lat_bucket{le="0.01"} 1' in text
    assert 'lat_bucket{le="0.1"} 3' in text
    assert 'lat_bucket{le="1"} 4' in text
    assert 'lat_bucket{le="+Inf"} 5' in text
    assert "lat_count 5" in text
    # bucket boundary: le is inclusive
    h2 = r.histogram("lat2", buckets=(0.01, 0.1, 1.0))
    h2.observe(0.1)
    assert 'lat2_bucket{le="0.1"} 1' in r.render_text()


def test_metrics_registry_same_instance():
    r = MetricsRegistry()
    assert r.counter("a") is r.counter("a")


def test_render_json():
    r = MetricsRegistry()
    r.counter("c").inc(3)
    r.histogram("h").observe(0.2)
    j = r.render_json()
    assert j["c"][""] == 3
    assert j["h"][""]["count"] == 1


def test_tracer_span_tree():
    t = RecordingTracer()
    set_tracer(t)
    try:
        with start_span("query", index="i") as root:
            with start_span("mapReduce"):
                with start_span("shard", shard=0):
                    pass
            with start_span("translate"):
                pass
        assert len(t.roots) == 1
        d = t.roots[0].to_dict()
        assert d["name"] == "query"
        assert d["tags"] == {"index": "i"}
        names = [c["name"] for c in d["children"]]
        assert names == ["mapReduce", "translate"]
        assert d["children"][0]["children"][0]["tags"] == {"shard": 0}
        assert d["duration_us"] >= 0
    finally:
        set_tracer(NopTracer())


def test_tracer_thread_isolation():
    t = RecordingTracer()
    set_tracer(t)
    try:
        def work(i):
            with start_span(f"root{i}"):
                with start_span("child"):
                    pass
        ts = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        [x.start() for x in ts]
        [x.join() for x in ts]
        assert len(t.roots) == 4
        for r in t.roots:
            assert len(r.children) == 1
    finally:
        set_tracer(NopTracer())


def test_nop_tracer_cheap():
    set_tracer(NopTracer())
    with start_span("x") as s:
        s.set_tag("a", 1)  # no-op, no error


def test_diagnostics_payload_and_version_check():
    from pilosa_tpu.obs.diagnostics import Diagnostics

    sent = []
    d = Diagnostics(version="1.2.3", send=sent.append)
    d.set("node_id", "n0")
    d.flush()
    assert sent and sent[0]["version"] == "1.2.3"
    assert sent[0]["node_id"] == "n0"
    assert sent[0]["num_cpu"] >= 1
    # reporting disabled: start() is a no-op, flush keeps local copy
    d2 = Diagnostics(version="x")
    assert d2.start()._thread is None
    d2.flush()
    assert d2.last_payload is not None
    assert Diagnostics.check_version("1.0.0", "1.2.0") is not None
    assert Diagnostics.check_version("2.0.0", "1.9.9") is None
    assert Diagnostics.check_version("2.0.0", "weird") is None


def test_performance_counters():
    from pilosa_tpu.obs.diagnostics import PerformanceCounters

    pc = PerformanceCounters()
    pc.add("queries", 3)
    pc.add("queries")
    pc.set_gauge("goroutines", 7)
    snap = pc.snapshot()
    assert snap == {"queries": 4, "goroutines": 7}
    assert '"queries": 4' in pc.dump_json()


def test_monitor_capture_and_http_wiring():
    from pilosa_tpu.obs.monitor import Monitor, global_monitor
    from pilosa_tpu.cluster.client import InternalClient, RemoteError
    from pilosa_tpu.server.http import Server
    import pytest as _pytest

    m = Monitor(keep=2)
    for i in range(3):
        try:
            raise ValueError(f"e{i}")
        except ValueError as e:
            m.capture_exception(e, query=f"q{i}")
    ev = m.recent()
    assert len(ev) == 2 and ev[-1]["message"] == "e2"
    assert "ValueError" in ev[-1]["traceback"]

    # a handler crash is captured by the global monitor and surfaced
    # at /debug/errors
    srv = Server().start()
    uri = f"127.0.0.1:{srv.port}"
    srv.add_route("GET", "/boom", lambda req: 1 / 0, admin_only=False)
    cli = InternalClient()
    try:
        before = len(global_monitor.recent())
        with _pytest.raises(RemoteError):
            cli._request(uri, "GET", "/boom")
        events = cli._request(uri, "GET", "/debug/errors")
        assert len(events) > before
        assert events[-1]["type"] == "ZeroDivisionError"
        # diagnostics + perf counters endpoints respond
        d = cli._request(uri, "GET", "/internal/diagnostics")
        assert "version" in d and "num_cpu" in d
        assert isinstance(
            cli._request(uri, "GET", "/internal/perf-counters"), dict)
    finally:
        srv.close()


class TestTesthook:
    """Resource leak auditor (testhook/hook.go, auditor.go analog)."""

    def test_open_close_cycle(self):
        from pilosa_tpu.obs import testhook
        if not testhook.ENABLED:
            import pytest
            pytest.skip("PILOSA_TPU_TESTHOOK disabled")
        obj = object()
        testhook.opened("unit.res", obj, "thing")
        assert "unit.res" in testhook.audit()
        assert testhook.audit()["unit.res"] == ["thing"]
        assert testhook.audit_stacks()["unit.res"]
        testhook.closed("unit.res", obj)
        assert "unit.res" not in testhook.audit()

    def test_rbf_db_tracked(self, tmp_path):
        from pilosa_tpu.obs import testhook
        from pilosa_tpu.storage import rbf
        if not testhook.ENABLED:
            import pytest
            pytest.skip("PILOSA_TPU_TESTHOOK disabled")
        db = rbf.DB(str(tmp_path / "x.rbf"))
        assert any(str(tmp_path) in d
                   for d in testhook.audit().get("rbf.DB", []))
        db.close()
        assert not any(str(tmp_path) in d
                       for d in testhook.audit().get("rbf.DB", []))


def test_histogram_quantiles_render():
    r = MetricsRegistry()
    lat = r.histogram("lat3", "latency", buckets=(0.01, 0.1, 1.0),
                      quantiles=(0.5, 0.99))
    for v in (0.005, 0.02, 0.05, 0.5, 0.9):
        lat.observe(v)
    # p50 falls in the (0.01, 0.1] bucket, interpolated
    q = lat.quantile(0.5)
    assert 0.01 < q <= 0.1
    assert lat.quantile(0.99) <= 1.0
    text = r.render_text()
    assert "lat3_p50 " in text
    assert "lat3_p99 " in text
    assert "# TYPE lat3_p50 gauge" in text


def test_histogram_quantile_empty_is_zero():
    r = MetricsRegistry()
    assert r.histogram("lat4", "x", quantiles=(0.5,)).quantile(0.5) == 0.0
