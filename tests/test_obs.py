"""Observability tests — logger, metrics exposition, tracing spans."""

import io
import threading

from pilosa_tpu.obs import (
    Logger,
    MetricsRegistry,
    NopTracer,
    RecordingTracer,
    set_tracer,
    start_span,
)
from pilosa_tpu.obs import logger as lg


def test_logger_levels_and_format():
    buf = io.StringIO()
    log = Logger(buf, level=lg.INFO)
    log.debug("hidden %d", 1)
    log.info("hello %s", "world")
    log.error("boom")
    out = buf.getvalue()
    assert "hidden" not in out
    assert "INFO" in out and "hello world" in out
    assert "ERROR" in out and "boom" in out


def test_logger_prefix():
    buf = io.StringIO()
    log = Logger(buf).with_prefix("executor")
    log.info("x")
    assert "[executor]" in buf.getvalue()


def test_counter_gauge_labels():
    r = MetricsRegistry()
    c = r.counter("q_total", "queries")
    c.inc()
    c.inc(2, index="i0")
    g = r.gauge("open_dbs")
    g.set(5)
    g.add(-1)
    text = r.render_text()
    assert "# TYPE q_total counter" in text
    assert "q_total 1" in text
    assert 'q_total{index="i0"} 2' in text
    assert "open_dbs 4" in text
    assert c.value(index="i0") == 2


def test_histogram_buckets():
    r = MetricsRegistry()
    h = r.histogram("lat", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    text = r.render_text()
    assert 'lat_bucket{le="0.01"} 1' in text
    assert 'lat_bucket{le="0.1"} 3' in text
    assert 'lat_bucket{le="1"} 4' in text
    assert 'lat_bucket{le="+Inf"} 5' in text
    assert "lat_count 5" in text
    # bucket boundary: le is inclusive
    h2 = r.histogram("lat2", buckets=(0.01, 0.1, 1.0))
    h2.observe(0.1)
    assert 'lat2_bucket{le="0.1"} 1' in r.render_text()


def test_metrics_registry_same_instance():
    r = MetricsRegistry()
    assert r.counter("a") is r.counter("a")


def test_render_json():
    r = MetricsRegistry()
    r.counter("c").inc(3)
    r.histogram("h").observe(0.2)
    j = r.render_json()
    assert j["c"][""] == 3
    assert j["h"][""]["count"] == 1


def test_tracer_span_tree():
    t = RecordingTracer()
    set_tracer(t)
    try:
        with start_span("query", index="i") as root:
            with start_span("mapReduce"):
                with start_span("shard", shard=0):
                    pass
            with start_span("translate"):
                pass
        assert len(t.roots) == 1
        d = t.roots[0].to_dict()
        assert d["name"] == "query"
        assert d["tags"] == {"index": "i"}
        names = [c["name"] for c in d["children"]]
        assert names == ["mapReduce", "translate"]
        assert d["children"][0]["children"][0]["tags"] == {"shard": 0}
        assert d["duration_us"] >= 0
    finally:
        set_tracer(NopTracer())


def test_tracer_thread_isolation():
    t = RecordingTracer()
    set_tracer(t)
    try:
        def work(i):
            with start_span(f"root{i}"):
                with start_span("child"):
                    pass
        ts = [threading.Thread(target=work, args=(i,)) for i in range(4)]
        [x.start() for x in ts]
        [x.join() for x in ts]
        assert len(t.roots) == 4
        for r in t.roots:
            assert len(r.children) == 1
    finally:
        set_tracer(NopTracer())


def test_nop_tracer_cheap():
    set_tracer(NopTracer())
    with start_span("x") as s:
        s.set_tag("a", 1)  # no-op, no error
