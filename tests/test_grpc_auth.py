"""gRPC service + authn/authz tests (server/grpc_test.go,
authn/authz test strategies)."""

import time

import grpc
import pytest

from pilosa_tpu.models.holder import Holder
from pilosa_tpu.api import API
from pilosa_tpu.server.authn import (
    AuthError,
    Authenticator,
    decode_jwt,
    encode_jwt,
)
from pilosa_tpu.server.authz import Authorizer
from pilosa_tpu.server.grpc import GRPCServer
from pilosa_tpu.server.proto import pb

SECRET = b"cluster-shared-secret"


@pytest.fixture()
def stack():
    holder = Holder()
    api = API(holder)
    srv = GRPCServer(api, bind="127.0.0.1:0").start()
    chan = grpc.insecure_channel(srv.uri)
    yield api, srv, chan
    chan.close()
    srv.stop()
    holder.close()


def _unary(chan, method, req, resp_cls):
    fn = chan.unary_unary(f"/proto.Pilosa/{method}",
                          request_serializer=req.SerializeToString,
                          response_deserializer=resp_cls.FromString)
    return fn(req)


def _stream(chan, method, req):
    fn = chan.unary_stream(f"/proto.Pilosa/{method}",
                           request_serializer=req.SerializeToString,
                           response_deserializer=pb.RowResponse.FromString)
    return list(fn(req))


def test_grpc_index_crud_and_pql(stack):
    api, srv, chan = stack
    _unary(chan, "CreateIndex", pb.CreateIndexRequest(name="g"),
           pb.CreateIndexResponse)
    got = _unary(chan, "GetIndexes", pb.GetIndexesRequest(),
                 pb.GetIndexesResponse)
    assert [i.name for i in got.indexes] == ["g"]

    api.create_field("g", "f", {"type": "set"})
    for col in (1, 2, 66000):
        api.query("g", f"Set({col}, f=7)")

    rows = _stream(chan, "QueryPQL",
                   pb.QueryPQLRequest(index="g", pql="Row(f=7)"))
    assert [r.columns[0].uint64Val for r in rows] == [1, 2, 66000]
    assert rows[0].headers[0].name == "_id"

    table = _unary(chan, "QueryPQLUnary",
                   pb.QueryPQLRequest(index="g", pql="Count(Row(f=7))"),
                   pb.TableResponse)
    assert table.rows[0].columns[0].uint64Val == 3

    # TopN pairs shape
    rows = _stream(chan, "QueryPQL",
                   pb.QueryPQLRequest(index="g", pql="TopN(f)"))
    assert rows[0].columns[0].uint64Val == 7
    assert rows[0].columns[1].uint64Val == 3

    _unary(chan, "DeleteIndex", pb.DeleteIndexRequest(name="g"),
           pb.DeleteIndexResponse)
    got = _unary(chan, "GetIndexes", pb.GetIndexesRequest(),
                 pb.GetIndexesResponse)
    assert not got.indexes


def test_grpc_profile_metadata(stack):
    """Profile=true over gRPC: ("profile", "true") invocation metadata
    returns the span tree as the profile-json trailing metadata entry
    (the wire message predates profiling)."""
    import json

    api, srv, chan = stack
    _unary(chan, "CreateIndex", pb.CreateIndexRequest(name="gp"),
           pb.CreateIndexResponse)
    api.create_field("gp", "f", {"type": "set"})
    api.query("gp", "Set(1, f=7)")

    fn = chan.unary_unary(
        "/proto.Pilosa/QueryPQLUnary",
        request_serializer=pb.QueryPQLRequest.SerializeToString,
        response_deserializer=pb.TableResponse.FromString)
    resp, call = fn.with_call(
        pb.QueryPQLRequest(index="gp", pql="Count(Row(f=7))"),
        metadata=(("profile", "true"),))
    assert resp.rows[0].columns[0].uint64Val == 1
    md = dict(call.trailing_metadata() or ())
    spans = json.loads(md["profile-json"])
    assert spans and spans[0]["name"] == "executor.Execute"
    # without the metadata flag no profile rides along
    resp, call = fn.with_call(
        pb.QueryPQLRequest(index="gp", pql="Count(Row(f=7))"))
    assert "profile-json" not in dict(call.trailing_metadata() or ())
    _unary(chan, "DeleteIndex", pb.DeleteIndexRequest(name="gp"),
           pb.DeleteIndexResponse)


def test_grpc_sql(stack):
    api, srv, chan = stack
    table = _unary(chan, "QuerySQLUnary", pb.QuerySQLRequest(
        sql="CREATE TABLE t (_id ID, v INT MIN 0 MAX 100)"),
        pb.TableResponse)
    _unary(chan, "QuerySQLUnary", pb.QuerySQLRequest(
        sql="INSERT INTO t (_id, v) VALUES (1, 42), (2, 58)"),
        pb.TableResponse)
    table = _unary(chan, "QuerySQLUnary", pb.QuerySQLRequest(
        sql="SELECT _id, v FROM t ORDER BY _id"), pb.TableResponse)
    assert [r.columns[1].int64Val for r in table.rows] == [42, 58]
    assert table.headers[1].name == "v"


def test_grpc_inspect(stack):
    api, srv, chan = stack
    api.create_index("ins")
    api.create_field("ins", "f", {"type": "set"})
    api.create_field("ins", "v", {"type": "int", "min": 0, "max": 99})
    api.query("ins", "Set(5, f=1)Set(5, f=2)")
    api.query("ins", "Set(5, v=42)")
    req = pb.InspectRequest(index="ins")
    req.columns.ids.vals.extend([5])
    rows = _stream(chan, "Inspect", req)
    assert rows[0].columns[0].uint64Val == 5
    by_name = {h.name: c for h, c in
               zip(rows[0].headers, rows[0].columns)}
    assert by_name["f"].stringVal == "1,2"
    assert by_name["v"].stringVal == "42"


def test_grpc_errors(stack):
    api, srv, chan = stack
    with pytest.raises(grpc.RpcError) as e:
        _unary(chan, "GetIndex", pb.GetIndexRequest(name="nope"),
               pb.GetIndexResponse)
    assert e.value.code() == grpc.StatusCode.NOT_FOUND
    with pytest.raises(grpc.RpcError) as e:
        _stream(chan, "QueryPQL",
                pb.QueryPQLRequest(index="nope", pql="Count(Row(f=1))"))
    assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT


# -- authn ---------------------------------------------------------------

def test_jwt_roundtrip_and_expiry():
    tok = encode_jwt({"sub": "u", "groups": ["g1"],
                      "exp": time.time() + 60}, SECRET)
    claims = decode_jwt(tok, SECRET)
    assert claims["sub"] == "u" and claims["groups"] == ["g1"]
    with pytest.raises(AuthError):
        decode_jwt(tok, b"wrong-secret")
    expired = encode_jwt({"exp": time.time() - 1}, SECRET)
    with pytest.raises(AuthError):
        decode_jwt(expired, SECRET)
    with pytest.raises(AuthError):
        decode_jwt("garbage", SECRET)


def test_authenticator_bearer_and_cache():
    a = Authenticator(SECRET, client_id="cid",
                      authorize_url="https://idp/authorize")
    tok = encode_jwt({"groups": ["g"], "exp": time.time() + 60}, SECRET)
    c1 = a.authenticate(f"Bearer {tok}")
    c2 = a.authenticate(tok)  # bare token + cache hit
    assert c1 == c2
    with pytest.raises(AuthError):
        a.authenticate("")
    assert "client_id=cid" in a.login_url()


# -- authz ---------------------------------------------------------------

def test_authorizer_levels():
    az = Authorizer(user_groups={
        "readers": {"sales": "read"},
        "writers": {"sales": "write"},
    }, admin_group="admins")
    assert az.allowed(["readers"], "sales", "read")
    assert not az.allowed(["readers"], "sales", "write")
    assert az.allowed(["writers", "readers"], "sales", "write")
    assert not az.allowed(["writers"], "hr", "read")
    assert az.allowed(["admins"], "anything", "admin")
    assert az.allowed_indexes(["readers"]) == ["sales"]
    assert az.allowed_indexes(["admins"]) == ["*"]


def test_authorizer_from_yaml(tmp_path):
    p = tmp_path / "policy.yaml"
    p.write_text(
        'user-groups:\n'
        '  "g1":\n'
        '    "idx": "write"\n'
        'admin: "root"\n')
    az = Authorizer.from_yaml(str(p))
    assert az.allowed(["g1"], "idx", "read")
    assert az.is_admin(["root"])


# -- HTTP middleware -----------------------------------------------------

def test_http_auth_middleware():
    from pilosa_tpu.cluster.client import InternalClient, RemoteError
    from pilosa_tpu.server.http import Server

    authn = Authenticator(SECRET)
    authz = Authorizer(user_groups={"writers": {"a": "write"}},
                       admin_group="admins")
    srv = Server(auth=(authn, authz)).start()
    uri = f"127.0.0.1:{srv.port}"
    cli = InternalClient()
    try:
        # no token -> 401
        with pytest.raises(RemoteError) as e:
            cli._request(uri, "POST", "/index/a", {})
        assert e.value.status == 401
        # /version stays open
        assert cli._request(uri, "GET", "/version")
        # writer token can create + query its index
        tok = encode_jwt({"groups": ["writers"],
                          "exp": time.time() + 60}, SECRET)
        hdrs = {"Authorization": f"Bearer {tok}"}
        cli2 = InternalClient(headers=hdrs)
        cli2._request(uri, "POST", "/index/a", {})
        # but not another index
        with pytest.raises(RemoteError) as e:
            cli2._request(uri, "POST", "/index/b", {})
        assert e.value.status == 403
        # nor admin-only schema writes
        with pytest.raises(RemoteError) as e:
            cli2._request(uri, "POST", "/schema", {"indexes": []})
        assert e.value.status == 403
        # admin token can
        atok = encode_jwt({"groups": ["admins"],
                           "exp": time.time() + 60}, SECRET)
        cli3 = InternalClient(headers={"Authorization": f"Bearer {atok}"})
        cli3._request(uri, "POST", "/schema", {"indexes": []})
        # login URL endpoint
        assert "url" in cli._request(uri, "GET", "/login")
    finally:
        srv.close()


def test_sql_authz_per_table(stack_auth=None):
    """SQL statements are authorized per table; SHOW TABLES filters."""
    holder = Holder()
    api = API(holder)
    authn = Authenticator(SECRET)
    authz = Authorizer(user_groups={
        "sales-rw": {"sales": "write"},
        "sales-ro": {"sales": "read"},
    }, admin_group="admins")
    srv = GRPCServer(api, auth=(authn, authz)).start()
    chan = grpc.insecure_channel(srv.uri)
    try:
        def md(groups):
            tok = encode_jwt({"groups": groups,
                              "exp": time.time() + 60}, SECRET)
            return (("authorization", f"Bearer {tok}"),)

        def sql(stmt, groups):
            fn = chan.unary_unary(
                "/proto.Pilosa/QuerySQLUnary",
                request_serializer=pb.QuerySQLRequest.SerializeToString,
                response_deserializer=pb.TableResponse.FromString)
            return fn(pb.QuerySQLRequest(sql=stmt), metadata=md(groups))

        sql("CREATE TABLE sales (_id ID, v INT MIN 0 MAX 9)",
            ["sales-rw"])
        api.create_index("secret")
        # read-only group can select but not insert
        sql("SELECT COUNT(*) FROM sales", ["sales-ro"])
        with pytest.raises(grpc.RpcError) as e:
            sql("INSERT INTO sales (_id, v) VALUES (1, 2)", ["sales-ro"])
        assert e.value.code() == grpc.StatusCode.PERMISSION_DENIED
        # no grant on secret at all
        with pytest.raises(grpc.RpcError) as e:
            sql("SELECT COUNT(*) FROM secret", ["sales-ro"])
        assert e.value.code() == grpc.StatusCode.PERMISSION_DENIED
        # SHOW TABLES only lists readable tables
        t = sql("SHOW TABLES", ["sales-ro"])
        assert [r.columns[1].stringVal for r in t.rows] == ["sales"]
        # GetIndexes filters the same way
        fn = chan.unary_unary(
            "/proto.Pilosa/GetIndexes",
            request_serializer=pb.GetIndexesRequest.SerializeToString,
            response_deserializer=pb.GetIndexesResponse.FromString)
        got = fn(pb.GetIndexesRequest(), metadata=md(["sales-ro"]))
        assert [i.name for i in got.indexes] == ["sales"]
    finally:
        chan.close()
        srv.stop()
        holder.close()


def test_http_read_token_can_query():
    """POST query with only read calls passes with a read grant; a
    write call in the same route needs write (chkAuthZ per-call)."""
    from pilosa_tpu.cluster.client import InternalClient, RemoteError
    from pilosa_tpu.server.http import Server

    authn = Authenticator(SECRET)
    authz = Authorizer(user_groups={"ro": {"a": "read"},
                                    "rw": {"a": "write"}})
    srv = Server(auth=(authn, authz)).start()
    uri = f"127.0.0.1:{srv.port}"
    rw = InternalClient(headers={"Authorization": "Bearer " + encode_jwt(
        {"groups": ["rw"], "exp": time.time() + 60}, SECRET)})
    ro = InternalClient(headers={"Authorization": "Bearer " + encode_jwt(
        {"groups": ["ro"], "exp": time.time() + 60}, SECRET)})
    try:
        rw._request(uri, "POST", "/index/a", {})
        rw._request(uri, "POST", "/index/a/field/f", {"type": "set"})
        rw._request(uri, "POST", "/index/a/query", {"query": "Set(1, f=1)"})
        r = ro._request(uri, "POST", "/index/a/query",
                        {"query": "Count(Row(f=1))"})
        assert r["results"] == [1]
        with pytest.raises(RemoteError) as e:
            ro._request(uri, "POST", "/index/a/query",
                        {"query": "Set(2, f=1)"})
        assert e.value.status == 403
    finally:
        srv.close()


def test_cluster_auth_token_peer_traffic():
    """Node-to-node traffic carries the bearer token so replication
    works with auth enabled."""
    from pilosa_tpu.cluster import ClusterNode, InMemDisCo

    authn = Authenticator(SECRET)
    tok = encode_jwt({"groups": ["admins"], "exp": time.time() + 3600},
                     SECRET)
    authz = Authorizer(admin_group="admins")
    disco = InMemDisCo(lease_ttl=1.0)
    nodes = [ClusterNode(f"n{i}", disco, holder=Holder(), replica_n=2,
                         auth=(authn, authz), auth_token=tok).open()
             for i in range(2)]
    try:
        nodes[0].apply_schema({"indexes": [{"name": "c", "fields": [
            {"name": "f", "options": {"type": "set"}}]}]})
        nodes[0].import_bits("c", "f", [1, 1], [0, 1 << 20])
        assert nodes[1].query("c", "Count(Row(f=1))")["results"] == [2]
    finally:
        for n in nodes:
            n.close()
