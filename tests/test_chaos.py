"""Failure-tolerance plane tests (ISSUE 6): the fault-injection
registry, client deadlines/retries, hedged replica reads, load-shed +
partial results, the kill/rejoin warm-start protocol, and the
sync_from_peers repair paths pinned directly.

The in-process cluster harness is real: ClusterNodes serve actual
HTTP between each other, so injected rpc faults strike genuine
sockets, not mocks."""

import os
import time

import pytest

from pilosa_tpu.cluster import (
    ClusterNode,
    Deadline,
    DeadlineExceeded,
    InMemDisCo,
    InternalClient,
    LoadShedError,
    NodeState,
    RemoteError,
)
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.models.view import VIEW_STANDARD
from pilosa_tpu.obs import faults, flight, metrics
from pilosa_tpu.taskpool import Pool, TaskFailure

SHARD = 1 << 20

SCHEMA = {"indexes": [{"name": "c", "fields": [
    {"name": "f", "options": {"type": "set"}},
    {"name": "v", "options": {"type": "int", "min": 0, "max": 1000}},
]}]}


@pytest.fixture(autouse=True)
def _clean_faults():
    """No fault armed in one test may leak into the next."""
    faults.clear()
    yield
    faults.clear()


@pytest.fixture()
def hedge_off(monkeypatch):
    """Deterministic fan-out: no speculative second attempts."""
    monkeypatch.setenv("PILOSA_TPU_CLUSTER_HEDGE_MS", "-1")


def _mk_cluster(n=3, replica_n=2, lease_ttl=0.6, hb=0.1):
    disco = InMemDisCo(lease_ttl=lease_ttl)
    holders = [Holder() for _ in range(n)]
    nodes = [ClusterNode(f"node{i}", disco, holder=holders[i],
                         replica_n=replica_n,
                         heartbeat_interval=hb).open()
             for i in range(n)]
    return disco, holders, nodes


def _close_all(nodes):
    for nd in nodes:
        try:
            nd.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_fault_registry_fire_take_match_times():
    # unarmed: free no-ops
    faults.fire("rpc-drop", "anything")
    assert faults.take("rpc-drop") is False
    # armed with a match + budget of 2
    faults.inject("rpc-drop", match="host-a", times=2)
    faults.fire("rpc-drop", "host-b/path")  # no match: no-op
    with pytest.raises(faults.InjectedFault):
        faults.fire("rpc-drop", "host-a/path")
    with pytest.raises(faults.InjectedFault):
        faults.fire("rpc-drop", "host-a/path")
    faults.fire("rpc-drop", "host-a/path")  # budget exhausted
    assert faults.active() == []
    # InjectedFault is network-shaped (rides failover paths)
    assert issubclass(faults.InjectedFault, ConnectionError)


def test_fault_registry_delay_only_and_unlimited():
    faults.inject("rpc-delay", times=0, delay_s=0.02)  # 0 = unlimited
    t0 = time.perf_counter()
    for _ in range(3):
        faults.fire("rpc-delay", "x")  # delay rule: sleeps, no raise
    assert time.perf_counter() - t0 >= 0.05
    assert faults.active()[0]["fired"] == 3


def test_fault_registry_spec_and_sources():
    n = faults.configure(
        "rpc-delay@10101,delay=5,times=3;node-crash@node2")
    assert n == 2
    pts = {r["point"]: r for r in faults.active()}
    assert pts["rpc-delay"]["match"] == "10101"
    assert pts["rpc-delay"]["remaining"] == 3
    assert pts["node-crash"]["match"] == "node2"
    # a test-armed rule survives a config re-arm; config rules don't
    faults.inject("torn-write")
    faults.configure("")
    assert [r["point"] for r in faults.active()] == ["torn-write"]
    with pytest.raises(ValueError):
        faults.configure("rpc-drop,bogus=1")


def test_inject_oom_is_registry_backed():
    from pilosa_tpu.memory import pressure
    pressure.inject_oom(2)
    assert [r["point"] for r in faults.active()] == ["device-oom"]
    assert pressure._take_injection() and pressure._take_injection()
    assert not pressure._take_injection()
    pressure.inject_oom(3)
    pressure.inject_oom(0)  # set-not-add semantics: 0 clears
    assert faults.active() == []


# ---------------------------------------------------------------------------
# client: deadlines, retries, classification
# ---------------------------------------------------------------------------

def test_remote_error_retryable_classification():
    assert RemoteError(503, "shed").retryable
    assert RemoteError(429, "slow down").retryable
    assert not RemoteError(400, "bad pql").retryable
    assert not RemoteError(404, "no index").retryable
    assert RemoteError(400, "x", retryable=True).retryable


def test_deadline_expiry_raises_before_connecting():
    c = InternalClient()
    d = Deadline(-0.01)  # already expired
    with pytest.raises(DeadlineExceeded):
        c.get_raw("127.0.0.1:1", "/status", deadline=d)


def test_client_retries_idempotent_reads_only(hedge_off):
    disco, _holders, nodes = _mk_cluster(n=1, replica_n=1)
    try:
        uri = nodes[0].uri
        c = InternalClient(retries=2, backoff_s=0.01)
        # one injected drop: the idempotent GET retries through it
        faults.inject("rpc-drop", match="/status", times=1)
        assert c.status(uri)["state"] is not None
        fired = metrics.FAULTS_TOTAL.value(point="rpc-drop")
        assert fired >= 1
        # non-idempotent POST does NOT retry: the drop surfaces
        faults.inject("rpc-drop", match="/index/c/query", times=1)
        nodes[0].apply_schema(SCHEMA)
        with pytest.raises(ConnectionError):
            c.query_node(uri, "c", "Count(Row(f=1))", None)
    finally:
        _close_all(nodes)


def test_client_retries_refused_connect_even_for_writes():
    """A refused connect sends ZERO bytes, so retrying is safe for
    any request — and a momentary accept-queue overflow on an
    overloaded-but-live node must not read as that node dying (the
    import path would otherwise declare 'no live replica' during a
    storm concentrated by a real peer death)."""
    calls = []

    class C(InternalClient):
        def _attempt(self, uri, method, path, data, content_type,
                     deadline, extra_headers=None):
            calls.append(path)
            if len(calls) == 1:
                raise ConnectionRefusedError(111, "refused")
            return 200, b'{"imported": 3}', {}

    c = C(retries=2, backoff_s=0.001)
    assert c.import_bits("x:1", "i", "f", [1], [2]) == 3  # POST, retried
    assert len(calls) == 2


# ---------------------------------------------------------------------------
# hedged reads + deadline propagation + partial results
# ---------------------------------------------------------------------------

def _seed(nodes, n_shards=4, per_shard=8):
    nodes[0].apply_schema(SCHEMA)
    rows, cols, vals = [], [], []
    for s in range(n_shards):
        for i in range(per_shard):
            rows.append(1 + i % 2)
            cols.append(s * SHARD + i * 31)
            vals.append(i * 10)
    nodes[0].import_bits("c", "f", rows, cols)
    nodes[0].import_values("c", "v", cols, vals)
    return len(cols)


def test_hedged_read_beats_slow_replica(monkeypatch):
    disco, _holders, nodes = _mk_cluster()
    try:
        n_bits = _seed(nodes)
        monkeypatch.setenv("PILOSA_TPU_CLUSTER_HEDGE_MS", "-1")
        expect = nodes[0].query("c", "Count(Row(f=1))")["results"]
        fired0 = metrics.CLUSTER_EVENTS.value(event="hedge_fired")
        won0 = metrics.CLUSTER_EVENTS.value(event="hedge_won")
        # every RPC to node1 stalls 2s; hedge fires at a fixed 25ms.
        # The wide margin (hedge path ~0.1s vs the 2s stall) keeps
        # the wall-clock assert honest on a loaded 2-core box where
        # scheduler jitter is hundreds of ms
        faults.inject("rpc-delay", match=nodes[1].uri, times=0,
                      delay_s=2.0)
        monkeypatch.setenv("PILOSA_TPU_CLUSTER_HEDGE_MS", "25")
        t0 = time.perf_counter()
        r = nodes[0].query("c", "Count(Row(f=1))")
        dt = time.perf_counter() - t0
        assert r["results"] == expect and "partial" not in r
        assert dt < 1.5, f"hedge did not rescue the query ({dt:.2f}s)"
        assert metrics.CLUSTER_EVENTS.value(event="hedge_fired") > fired0
        assert metrics.CLUSTER_EVENTS.value(event="hedge_won") > won0
        # the slow-but-alive primary is NOT marked DOWN (slow != dead)
        assert disco.nodes()[1].state == NodeState.STARTED
        assert n_bits  # silence linters; seed really imported
    finally:
        _close_all(nodes)


def test_hedge_covers_whole_group_or_waits(monkeypatch):
    """replica_n=1: no alternate owners exist, so hedging must NOT
    fire a half-covered speculative attempt — the delayed primary
    answer is the only correct one."""
    disco, _holders, nodes = _mk_cluster(replica_n=1)
    try:
        _seed(nodes)
        monkeypatch.setenv("PILOSA_TPU_CLUSTER_HEDGE_MS", "-1")
        expect = nodes[0].query("c", "Count(Row(f=1))")["results"]
        fired0 = metrics.CLUSTER_EVENTS.value(event="hedge_fired")
        faults.inject("rpc-delay", match=nodes[1].uri, times=0,
                      delay_s=0.15)
        monkeypatch.setenv("PILOSA_TPU_CLUSTER_HEDGE_MS", "20")
        r = nodes[0].query("c", "Count(Row(f=1))")
        assert r["results"] == expect
        assert metrics.CLUSTER_EVENTS.value(
            event="hedge_fired") == fired0
    finally:
        _close_all(nodes)


def test_load_shed_typed_503_and_partial_results(hedge_off):
    disco, _holders, nodes = _mk_cluster(replica_n=1)
    try:
        _seed(nodes)
        full = nodes[0].query("c", "Count(Row(f=1))")["results"][0]
        victim = nodes[2]
        victim.pause()
        # default: typed 503 load-shed, not a silent under-count
        with pytest.raises(LoadShedError) as ei:
            nodes[0].query("c", "Count(Row(f=1))")
        assert ei.value.status == 503
        assert ei.value.missing_shards
        assert metrics.CLUSTER_EVENTS.value(event="load_shed") > 0
        # partial mode: Count serves the live subset, explicitly
        # flagged with the missing shards
        r = nodes[0].query("c", "Count(Row(f=1))", partial_ok=True)
        assert r["partial"]["missing_shards"] == ei.value.missing_shards
        assert 0 < r["results"][0] < full
        # TopN is partial-eligible too
        r2 = nodes[0].query("c", "TopN(f, n=2)", partial_ok=True)
        assert "partial" in r2 and r2["results"][0]
        # a Row query is NOT (its column set would be silently wrong)
        with pytest.raises(LoadShedError):
            nodes[0].query("c", "Row(f=1)", partial_ok=True)
    finally:
        _close_all(nodes)


def test_partial_reduce_is_exact_even_with_zero_live_shards(hedge_off):
    """Partial mode reduces to the call's ZERO value when every shard
    is missing — never a meaningless None Count (each live shard
    contributes exactly 4 f=1 bits in this seed, so the partial answer
    is exact for whatever subset survives)."""
    disco, _holders, nodes = _mk_cluster(n=2, replica_n=1)
    try:
        _seed(nodes)
        full = nodes[0].query("c", "Count(Row(f=1))")["results"][0]
        nodes[1].pause()
        r = nodes[0].query("c", "Count(Row(f=1))", partial_ok=True)
        got = r["results"][0]
        missing = r["partial"]["missing_shards"]
        assert isinstance(got, int) and missing
        assert got == full - 4 * len(missing)
    finally:
        _close_all(nodes)


def test_deadline_propagates_and_bounds_the_query(hedge_off):
    disco, _holders, nodes = _mk_cluster(replica_n=1)
    try:
        _seed(nodes)
        nodes[0].query("c", "Count(Row(f=1))")  # warm
        # both remote nodes stall well past the deadline (the
        # injected delay models network time, so it burns budget)
        faults.inject("rpc-delay", match=nodes[1].uri, times=0,
                      delay_s=1.0)
        faults.inject("rpc-delay", match=nodes[2].uri, times=0,
                      delay_s=1.0)
        t0 = time.perf_counter()
        with pytest.raises(DeadlineExceeded) as ei:
            # per-attempt budgets derive from the end-to-end deadline;
            # once it burns the query fails AS a deadline error (HTTP
            # 504) — never a 503 blaming replicas for the caller's
            # own exhausted budget, and never stacking the full
            # per-node delays serially on top of retries
            nodes[0].query("c", "Count(Row(f=1))", deadline_s=0.2)
        assert ei.value.status == 504
        # one injected 1s sleep bounds the floor; stacked re-plans
        # would cost ~3s+ — the gap absorbs loaded-box jitter
        assert time.perf_counter() - t0 < 2.4
        # the healthy-but-slow nodes were NOT globally marked DOWN by
        # the caller's deadline running out
        assert all(n.state == NodeState.STARTED for n in disco.nodes())
    finally:
        _close_all(nodes)


# ---------------------------------------------------------------------------
# kill / rejoin (node-crash fault + warm start)
# ---------------------------------------------------------------------------

def test_node_crash_fault_then_warm_start_rejoin(hedge_off):
    disco, holders, nodes = _mk_cluster()
    prev = (flight.recorder.enabled, flight.recorder._ring.maxlen)
    flight.recorder.configure(enabled=True, keep=2048)
    try:
        _seed(nodes)
        queries = ["Count(Row(f=1))", "Row(f=2)",
                   "Sum(Row(f=1), field=v)"]
        expected = {q: nodes[0].query("c", q)["results"]
                    for q in queries}
        for q in queries:  # flight records feed the rejoin prefill
            nodes[0].query("c", q)
        # the node-crash fault fires inside the victim's OWN heartbeat
        # loop: it pauses (socket closed, beats stop) mid-traffic
        faults.inject("node-crash", match="node2")
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                InternalClient(timeout=0.5, retries=0).status(
                    nodes[2].uri)
            except Exception:
                break
            time.sleep(0.05)
        else:
            pytest.fail("node-crash fault never fired")
        # cluster serves through the death, bit-exact
        for q in queries:
            assert nodes[0].query("c", q)["results"] == expected[q]
        # writes the dead node misses (row outside the read mix)
        nodes[0].import_bits("c", "f", [7, 7], [3, SHARD + 3])
        # warm-start rejoin: resync + prefill BEFORE taking traffic
        rejoined = ClusterNode("node2", disco, holder=holders[2],
                               replica_n=2,
                               heartbeat_interval=0.1).open(warm=True)
        nodes[2] = rejoined
        assert rejoined.warm_stats["sync"]["blocks"] > 0
        assert rejoined.warm_stats["prefilled"] > 0
        # the while-down write reached the rejoined node's replicas
        assert rejoined.query("c", "Count(Row(f=7))")["results"] == [2]
        for q in queries:  # fan-out THROUGH the rejoined node
            assert rejoined.query("c", q)["results"] == expected[q]
        assert metrics.CLUSTER_EVENTS.value(event="node_rejoin") > 0
    finally:
        flight.recorder.configure(enabled=prev[0], keep=prev[1])
        _close_all(nodes)


def test_heartbeat_stall_marks_down_then_rejoin_on_revive():
    disco, _holders, nodes = _mk_cluster(n=2, replica_n=1,
                                         lease_ttl=0.3)
    try:
        faults.inject("heartbeat-stall", match="node1", times=0)
        deadline = time.time() + 5
        while time.time() < deadline:
            if disco.nodes()[1].state == NodeState.DOWN:
                break
            time.sleep(0.05)
        else:
            pytest.fail("stalled node never marked DOWN")
        # heal the stall: the next beat revives the lease (node_rejoin)
        rejoin0 = metrics.CLUSTER_EVENTS.value(event="node_rejoin")
        faults.clear("heartbeat-stall")
        deadline = time.time() + 5
        while time.time() < deadline:
            if disco.nodes()[1].state == NodeState.STARTED:
                break
            time.sleep(0.05)
        else:
            pytest.fail("revived node never rejoined")
        assert metrics.CLUSTER_EVENTS.value(
            event="node_rejoin") > rejoin0
    finally:
        _close_all(nodes)


# ---------------------------------------------------------------------------
# sync_from_peers repair paths, pinned directly
# ---------------------------------------------------------------------------

KEYED_SCHEMA = {"indexes": [
    {"name": "c", "fields": [
        {"name": "f", "options": {"type": "set"}}]},
    {"name": "k", "keys": True, "fields": [
        {"name": "g", "options": {"type": "set", "keys": True}}]},
]}


def test_sync_pulls_newer_keys_from_live_replica(hedge_off):
    """Partition snapshots pull from a LIVE owner even when the
    rejoining node is itself the jump-hash primary — the replicas
    that stayed up hold the newer keys."""
    disco, holders, nodes = _mk_cluster(replica_n=3)
    try:
        nodes[0].apply_schema(KEYED_SCHEMA)
        nodes[0].query("k", 'Set("seed", g="x")')
        victim = nodes[2]
        victim.pause()
        time.sleep(0.8)  # lease expires, node2 marked DOWN
        # keys created while down, some of whose partitions node2
        # primaries (replica_n=3: every node owns every partition)
        for i in range(8):
            nodes[0].query("k", f'Set("down-{i}", g="y")')
        rejoined = ClusterNode("node2", disco, holder=holders[2],
                               replica_n=3,
                               heartbeat_interval=0.1).open()
        nodes[2] = rejoined
        stats = rejoined.sync_from_peers()
        assert stats["partitions"] > 0 and stats["fields"] > 0
        kidx = rejoined.api.holder.index("k")
        want = {f"down-{i}" for i in range(8)} | {"seed"}
        got = set(kidx.column_translator.find_keys(*want))
        assert got == want
        assert set(kidx.field("g").row_translator
                   .find_keys("x", "y")) == {"x", "y"}
    finally:
        _close_all(nodes)


def test_sync_no_live_replica_fallback_to_reporting_peer(hedge_off):
    """replica_n=1: partitions whose single owner is the rejoining
    node itself have NO live replica — sync must fall back to the
    peer that reported the partition instead of skipping the keys."""
    disco, holders, nodes = _mk_cluster(n=2, replica_n=1)
    try:
        nodes[0].apply_schema(KEYED_SCHEMA)
        victim = nodes[1]
        victim.pause()
        time.sleep(0.8)
        # create keys LOCALLY on node0 (api path, no cluster routing):
        # whatever partition they hash to, node0's store holds them
        keys = [f"orphan-{i}" for i in range(32)]
        for k in keys:
            nodes[0].api.query("k", f'Set("{k}", g="z")')
        rejoined = ClusterNode("node1", disco, holder=holders[1],
                               replica_n=1,
                               heartbeat_interval=0.1).open()
        nodes[1] = rejoined
        # at least one key's partition must be primaried by node1 for
        # the fallback branch to be exercised
        snap = rejoined.snapshot()
        assert any(snap.key_nodes("k", k)[0].id == "node1"
                   for k in keys)
        stats = rejoined.sync_from_peers()
        assert stats["partitions"] > 0
        kidx = rejoined.api.holder.index("k")
        assert set(kidx.column_translator.find_keys(*keys)) == set(keys)
    finally:
        _close_all(nodes)


def test_fragment_block_repair_restores_diverged_bits(hedge_off):
    disco, _holders, nodes = _mk_cluster(n=2, replica_n=2)
    try:
        _seed(nodes, n_shards=2)
        ex = nodes[1].api.executor
        before = ex.execute("c", "Count(Row(f=1))")[0]
        # diverge node1's replica behind the cluster's back
        frag = nodes[1].api.holder.index("c").field("f") \
            .view(VIEW_STANDARD).fragment(0)
        frag.clear_bit(1, 0)
        frag.clear_bit(1, 31)
        assert ex.execute("c", "Count(Row(f=1))")[0] < before
        stats = nodes[1].sync_from_peers()
        assert stats["blocks"] > 0
        assert ex.execute("c", "Count(Row(f=1))")[0] == before
    finally:
        _close_all(nodes)


def test_torn_tail_translate_snapshot_restart(tmp_path):
    """A crash mid-append (torn-write fault) leaves a torn final log
    line; restart drops exactly that record, and a peer snapshot
    restore heals the store to the authoritative state."""
    from pilosa_tpu.storage.translate import TranslateStore
    p = str(tmp_path / "keys.jsonl")
    st = TranslateStore(path=p, index="i")
    id_alpha = st.create_keys("alpha")["alpha"]
    faults.inject("torn-write", match=p)
    # the append tears mid-record and the store dies like a crash
    # (raises + closes its log: nothing may land AFTER the torn tail,
    # or restart recovery couldn't absorb it as the last line)
    with pytest.raises(faults.InjectedFault):
        st.create_keys("beta")
    st.close()
    st2 = TranslateStore(path=p, index="i")
    assert st2.find_keys("alpha") == {"alpha": id_alpha}
    assert st2.find_keys("beta") == {}  # torn tail dropped, not poison
    # the peer that stayed up holds both keys; snapshot restore heals
    donor = TranslateStore(index="i")
    donor.create_keys("alpha")
    id_beta = donor.create_keys("beta")["beta"]
    st2.restore_snapshot(donor.snapshot())
    assert st2.find_keys("beta") == {"beta": id_beta}
    # and the healed store survives ANOTHER restart intact
    st2.close()
    st3 = TranslateStore(path=p, index="i")
    assert st3.find_keys("alpha", "beta") == {"alpha": id_alpha,
                                              "beta": id_beta}
    st3.close()
    donor.close()


# ---------------------------------------------------------------------------
# serving fault point, flight attempts, debug/metrics surfaces
# ---------------------------------------------------------------------------

def test_serving_dispatch_fault_degrades_to_direct():
    from pilosa_tpu.executor.executor import Executor
    h = Holder()
    idx = h.create_index("i")
    idx.create_field("a")
    ex = Executor(h)
    for c in range(100):
        ex.execute("i", f"Set({c}, a={c % 3})")
    ex.enable_serving(window_s=0.0005, max_batch=8, cache_bytes=0)
    want = ex.execute("i", "Count(Row(a=1))")
    fired0 = metrics.FAULTS_TOTAL.value(point="serving-dispatch")
    faults.inject("serving-dispatch", times=1)
    got = ex.execute_serving("i", "Count(Row(a=1))")
    assert got == want  # rider fell back to direct, answer exact
    assert metrics.FAULTS_TOTAL.value(
        point="serving-dispatch") > fired0


def test_cluster_flight_record_carries_attempts(hedge_off):
    disco, _holders, nodes = _mk_cluster()
    prev = (flight.recorder.enabled, flight.recorder._ring.maxlen)
    flight.recorder.configure(enabled=True, keep=64)
    try:
        _seed(nodes)
        flight.recorder.clear()
        nodes[0].query("c", "Count(Row(f=1))")
        rec = next(r for r in flight.recorder.recent(10)
                   if r.get("route") == "cluster")
        assert rec["attempts"], rec
        assert {a["outcome"] for a in rec["attempts"]} <= \
            {"ok", "error", "hedge_ok", "ok-local", "hedge_ok-local"}
        assert all(a["ms"] >= 0 for a in rec["attempts"])
    finally:
        flight.recorder.configure(enabled=prev[0], keep=prev[1])
        _close_all(nodes)


def test_debug_faults_endpoint_and_cluster_metrics(hedge_off):
    disco, _holders, nodes = _mk_cluster(n=1, replica_n=1)
    try:
        faults.inject("rpc-delay", match="nowhere", times=5,
                      delay_s=0.001)
        c = InternalClient()
        out = c.get_json(nodes[0].uri, "/debug/faults")
        assert out["faults"][0]["point"] == "rpc-delay"
        assert out["faults"][0]["remaining"] == 5
        disco.check_heartbeats()  # exports heartbeat-age gauges
        text = c.get_raw(nodes[0].uri, "/metrics").decode()
        assert "pilosa_cluster_heartbeat_age_seconds" in text
        assert "pilosa_cluster_events_total" in text
        assert "pilosa_fault_injections_total" in text
    finally:
        _close_all(nodes)


def test_http_maps_typed_status_errors():
    """A status-carrying exception escaping a handler keeps its code
    (LoadShedError 503) instead of collapsing into a 500."""
    from pilosa_tpu.server.http import Server

    class _Req:
        vars = {}
        query = {}
        headers = {}

    srv = Server(holder=Holder())
    try:
        def boom(req):
            raise LoadShedError("shards down", missing_shards=[3])
        srv.add_route("GET", "/boom", boom, admin_only=False)
        req = _Req()
        status, body = srv.dispatch("GET", "/boom", req)
        assert status == 503
        assert body["type"] == "LoadShedError"
        # a shed is retryable by contract: the 503 carries Retry-After
        assert req.extra_headers == {"Retry-After": "1"}
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# hedge-delay derivation
# ---------------------------------------------------------------------------

def test_derive_hedge_delay_resists_slow_replica_poisoning():
    from pilosa_tpu.cluster.coordinator import derive_hedge_delay_s
    prev = (flight.recorder.enabled, flight.recorder._ring.maxlen)
    flight.recorder.configure(enabled=True, keep=512)
    flight.recorder.clear()
    try:
        # no records yet: the default
        assert derive_hedge_delay_s(default_s=0.077) == 0.077
        # a cluster where 1 of 3 replicas stalls at 500ms: record
        # durations are ALL ~500ms (every fan-out touches the slow
        # node) but 2/3 of attempts stay fast
        for i in range(100):
            flight.recorder.record({
                "duration_ms": 500.0, "route": "cluster",
                "attempts": [
                    {"node": "a", "ms": 8.0, "outcome": "ok"},
                    {"node": "b", "ms": 10.0, "outcome": "ok"},
                    {"node": "slow", "ms": 500.0, "outcome": "ok"},
                ]})
        d = derive_hedge_delay_s()
        # anchored to the healthy majority (3 x ~10ms), nowhere near
        # the 500ms the record-level p99 would have derived
        assert d < 0.1, d
    finally:
        flight.recorder.configure(enabled=prev[0], keep=prev[1])
        flight.recorder.clear()
