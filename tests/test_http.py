"""HTTP server + API facade tests — drive the reference's route
surface (http_handler.go:493-562) over a live in-process server."""

import json

import http.client

import pytest

from pilosa_tpu.server import Server


@pytest.fixture()
def srv():
    s = Server().start()
    yield s
    s.close()


def req(srv, method, path, body=None, timeout=10):
    c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                   timeout=timeout)
    data = json.dumps(body) if isinstance(body, (dict, list)) else body
    c.request(method, path, body=data,
              headers={"Content-Type": "application/json"})
    r = c.getresponse()
    raw = r.read()
    c.close()
    try:
        return r.status, json.loads(raw)
    except json.JSONDecodeError:
        return r.status, raw.decode()


def test_version_info_status(srv):
    st, v = req(srv, "GET", "/version")
    assert st == 200 and "version" in v
    st, info = req(srv, "GET", "/info")
    assert st == 200 and info["shard_width"] == 1 << 20
    st, s = req(srv, "GET", "/status")
    assert st == 200 and s["state"] == "NORMAL"


def test_index_field_lifecycle(srv):
    st, d = req(srv, "POST", "/index/i0", {"options": {"keys": False}})
    assert st == 200 and d["name"] == "i0"
    st, d = req(srv, "POST", "/index/i0", {})
    assert st == 409
    st, d = req(srv, "POST", "/index/i0/field/f0", {"options": {"type": "set"}})
    assert st == 200 and d["name"] == "f0"
    st, sch = req(srv, "GET", "/schema")
    names = [ix["name"] for ix in sch["indexes"]]
    assert "i0" in names
    st, _ = req(srv, "DELETE", "/index/i0/field/f0")
    assert st == 200
    st, _ = req(srv, "DELETE", "/index/i0")
    assert st == 200
    st, _ = req(srv, "DELETE", "/index/i0")
    assert st == 404


def test_invalid_names(srv):
    st, d = req(srv, "POST", "/index/BadName", {})
    assert st == 400 and "error" in d


def test_query_roundtrip(srv):
    req(srv, "POST", "/index/i1", {})
    req(srv, "POST", "/index/i1/field/f", {})
    st, d = req(srv, "POST", "/index/i1/query",
                {"query": "Set(1, f=10) Set(2, f=10) Set(1, f=20)"})
    assert st == 200 and d["results"] == [True, True, True]
    st, d = req(srv, "POST", "/index/i1/query",
                {"query": "Count(Row(f=10))"})
    assert d["results"] == [2]
    st, d = req(srv, "POST", "/index/i1/query",
                {"query": "Row(f=10)"})
    assert d["results"][0]["columns"] == [1, 2]
    # raw PQL body (text/plain mode)
    st, d = req(srv, "POST", "/index/i1/query", "Count(Row(f=20))")
    assert d["results"] == [1]
    # bad query
    st, d = req(srv, "POST", "/index/i1/query", {"query": "Nope("})
    assert st == 400 and "error" in d


def test_query_profile(srv):
    req(srv, "POST", "/index/ip", {})
    req(srv, "POST", "/index/ip/field/f", {})
    st, d = req(srv, "POST", "/index/ip/query?profile=true",
                {"query": "Count(Row(f=1))"})
    assert st == 200
    prof = d["profile"]
    assert prof and prof[0]["name"] == "executor.Execute"


def test_import_bits_and_values(srv):
    req(srv, "POST", "/index/i2", {})
    req(srv, "POST", "/index/i2/field/f", {})
    req(srv, "POST", "/index/i2/field/b",
        {"options": {"type": "int", "min": 0, "max": 1000}})
    st, d = req(srv, "POST", "/index/i2/field/f/import",
                {"rows": [1, 1, 2], "columns": [10, 11, 10]})
    assert st == 200 and d["imported"] == 3
    st, d = req(srv, "POST", "/index/i2/field/b/import",
                {"columns": [10, 11], "values": [7, 9]})
    assert st == 200 and d["imported"] == 2
    st, d = req(srv, "POST", "/index/i2/query", {"query": "Sum(field=b)"})
    assert d["results"][0] == {"value": 16, "count": 2}
    # clear
    st, d = req(srv, "POST", "/index/i2/field/f/import",
                {"rows": [1], "columns": [10], "clear": True})
    assert d["imported"] == 1
    st, d = req(srv, "POST", "/index/i2/query", {"query": "Count(Row(f=1))"})
    assert d["results"] == [1]


def test_keyed_import_and_translate(srv):
    req(srv, "POST", "/index/k", {"options": {"keys": True}})
    req(srv, "POST", "/index/k/field/f", {"options": {"keys": True}})
    st, d = req(srv, "POST", "/index/k/field/f/import",
                {"rowKeys": ["red", "red", "blue"],
                 "columnKeys": ["a", "b", "a"]})
    assert st == 200 and d["imported"] == 3
    st, d = req(srv, "POST", "/index/k/query", {"query": 'Row(f="red")'})
    assert sorted(d["results"][0]["keys"]) == ["a", "b"]
    # translate endpoints
    st, ids = req(srv, "POST", "/internal/translate/k/keys/find",
                  {"keys": ["a", "zzz"]})
    assert st == 200 and ids[0] is not None and ids[1] is None
    st, ids = req(srv, "POST", "/internal/translate/k/keys/create",
                  {"keys": ["new1"]})
    assert st == 200 and isinstance(ids[0], int)
    st, keys = req(srv, "POST", "/internal/translate/k/ids",
                   {"ids": [ids[0]]})
    assert keys == ["new1"]


def test_sql_over_http(srv):
    st, _ = req(srv, "POST", "/sql",
                {"sql": "CREATE TABLE t (_id id, n int min 0 max 100)"})
    assert st == 200
    st, _ = req(srv, "POST", "/sql",
                {"sql": "INSERT INTO t (_id, n) VALUES (1, 5), (2, 7)"})
    assert st == 200
    st, d = req(srv, "POST", "/sql", {"sql": "SELECT COUNT(*) FROM t"})
    assert st == 200 and d["data"] == [[2]]
    assert d["schema"]["fields"]
    st, d = req(srv, "POST", "/sql", {"sql": "SELECT bogus FROM nope"})
    assert st == 400


def test_schema_apply_idempotent(srv):
    schema = {"indexes": [
        {"name": "sa", "keys": False,
         "fields": [{"name": "f", "options": {"type": "set"}},
                    {"name": "n", "options": {"type": "int",
                                              "min": 0, "max": 10}}]}]}
    st, _ = req(srv, "POST", "/schema", schema)
    assert st == 200
    st, _ = req(srv, "POST", "/schema", schema)  # idempotent
    assert st == 200
    st, sch = req(srv, "GET", "/schema")
    ix = [i for i in sch["indexes"] if i["name"] == "sa"][0]
    assert {f["name"] for f in ix["fields"]} >= {"f", "n"}


def test_metrics_and_history(srv):
    req(srv, "POST", "/index/m", {})
    req(srv, "POST", "/index/m/field/f", {})
    req(srv, "POST", "/index/m/query", {"query": "Count(Row(f=1))"})
    st, text = req(srv, "GET", "/metrics")
    assert st == 200 and "pilosa_query_total" in text
    st, j = req(srv, "GET", "/metrics.json")
    assert st == 200 and "pilosa_query_total" in j
    st, hist = req(srv, "GET", "/query-history")
    assert st == 200
    assert any(h["query"].startswith("Count") for h in hist)


def test_shards_max(srv):
    req(srv, "POST", "/index/sm", {})
    req(srv, "POST", "/index/sm/field/f", {})
    req(srv, "POST", "/index/sm/query",
        {"query": f"Set({3 * (1 << 20) + 5}, f=1)"})
    st, d = req(srv, "GET", "/internal/shards/max")
    assert st == 200 and d["standard"]["sm"] == 3


def test_404(srv):
    st, d = req(srv, "GET", "/nope")
    assert st == 404


def test_debug_profile_endpoints(srv):
    """pprof/fgprof analogs (http_handler.go:493-494): stack sampler,
    heap snapshot, slow-query ring.  Generous client timeouts: the
    0.2s sampling window and the tracemalloc snapshot both stretch by
    an order of magnitude when the full suite loads the 1-CPU CI box
    (GIL starvation), and a tight timeout here flakes."""
    st, body = req(srv, "GET", "/debug/profile?seconds=0.2&hz=50",
                   timeout=60)
    assert st == 200 and "stack samples" in body
    st, body = req(srv, "GET", "/debug/allocs", timeout=60)
    assert st == 200 and ("tracemalloc" in body or "heap:" in body)
    # second call must produce a real snapshot
    st, body = req(srv, "GET", "/debug/allocs", timeout=60)
    assert st == 200 and "heap:" in body


def test_long_query_log(srv):
    srv.api.long_query_time = 1e-9  # everything is "slow"
    req(srv, "POST", "/index/lq", {})
    req(srv, "POST", "/index/lq/field/f", {})
    req(srv, "POST", "/index/lq/query", {"query": "Set(1, f=1)"})
    req(srv, "POST", "/index/lq/query", {"query": "Count(Row(f=1))"})
    st, entries = req(srv, "GET", "/debug/long-queries")
    assert st == 200 and len(entries) >= 2
    top = entries[0]
    assert top["query"] == "Count(Row(f=1))"
    assert top["runtime_ns"] > 0
    # span timings ride along (server.go:201 long-query log + spans)
    assert top["spans"] and top["spans"][0]["name"] == "executor.Execute"


def test_long_query_log_off_by_default(srv):
    req(srv, "POST", "/index/lq2", {})
    st, entries = req(srv, "GET", "/debug/long-queries")
    assert st == 200 and entries == []


def test_decimal_over_http(srv):
    """Decimal values serialize as JSON numbers end-to-end."""
    st, _ = req(srv, "POST", "/sql", {"sql":
        "CREATE TABLE d (_id id, p decimal(2))"})
    assert st == 200
    st, _ = req(srv, "POST", "/sql", {"sql":
        "INSERT INTO d (_id, p) VALUES (1, '10.50'), (2, '104.99')"})
    assert st == 200
    st, r = req(srv, "POST", "/sql", {"sql": "SELECT sum(p) FROM d"})
    assert st == 200 and r["data"] == [[115.49]], r
    st, r = req(srv, "POST", "/index/d/query",
                {"query": "Sum(field=p)"})
    assert st == 200 and r["results"][0]["value"] == 115.49, r
