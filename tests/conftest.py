"""Test config: run JAX on a virtual 8-device CPU mesh.

This is the analog of the reference's in-process multi-node cluster
harness (test/cluster.go:31 MustRunCluster): instead of N server
processes with embedded etcd, we get N XLA host devices so every
sharding/collective path compiles and runs in one process.
"""

import os

# Must be set before jax is imported anywhere.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Resource leak auditing (testhook/ analog): must be set before
# pilosa_tpu.obs.testhook is imported anywhere.
os.environ.setdefault("PILOSA_TPU_TESTHOOK", "1")

import jax  # noqa: E402

# The axon sitecustomize force-selects the TPU platform via
# jax.config.update("jax_platforms", "axon,cpu"), overriding the env
# var — override it back before any backend initializes.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0x5EED)


@pytest.fixture(autouse=True)
def _fresh_stats_catalog():
    """Per-test isolation of the process statistics catalog
    (obs/stats.py).  Fingerprint profiles are keyed by
    (index, query, shards), and test files reuse the same tiny index
    and query names — a cost profile learned under one test's load
    would reclassify another test's admission (e.g. a point read
    profiled slow earlier would class HEAVY and block forever on a
    deliberately saturated gate).  Clearing the in-memory planes per
    test keeps the stats integration exercised within each test with
    no cross-test order dependence; stats tests that need
    persistence swap in their own catalog."""
    from pilosa_tpu.obs import stats
    stats.get().clear()
    yield


@pytest.fixture(scope="session", autouse=True)
def _leak_audit():
    """Session-end resource audit (testhook/auditor.go): every rbf
    DB, HTTP server, and spill set opened by the suite must have been
    closed."""
    yield
    from pilosa_tpu.obs import testhook
    if not testhook.ENABLED:
        return
    leaks = testhook.audit()
    assert not leaks, (
        f"leaked resources at session end: {leaks}\n"
        "opening stacks:\n"
        + "\n".join("\n".join(v)
                    for v in testhook.audit_stacks().values()))
