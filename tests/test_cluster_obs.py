"""Cluster-scope observability tests (ISSUE 10): cross-node trace
propagation through the real HTTP data plane, hedged attempts as
parallel node lanes in /debug/trace, the federated
/debug/cluster/{queries,metrics} views with per-node timeouts +
partial flagging, and the gRPC trace-metadata twin.

Same real-harness rule as test_chaos: ClusterNodes serve actual HTTP
between each other, so trace headers and response trailers cross
genuine sockets."""

import json
import time

import pytest

from pilosa_tpu.cluster import ClusterNode, InMemDisCo
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.obs import faults, flight, metrics

SHARD = 1 << 20

SCHEMA = {"indexes": [{"name": "c", "fields": [
    {"name": "f", "options": {"type": "set"}},
]}]}


@pytest.fixture(autouse=True)
def _clean(request):
    faults.clear()
    prev = (flight.recorder.enabled, flight.recorder._ring.maxlen)
    flight.recorder.configure(enabled=True, keep=256)
    yield
    faults.clear()
    flight.recorder.clear()  # node lanes must not leak across tests
    flight.recorder.configure(enabled=prev[0], keep=prev[1])


@pytest.fixture()
def hedge_off(monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_CLUSTER_HEDGE_MS", "-1")


def _mk_cluster(n=3, replica_n=2, lease_ttl=5.0, hb=5.0):
    disco = InMemDisCo(lease_ttl=lease_ttl)
    holders = [Holder() for _ in range(n)]
    nodes = [ClusterNode(f"node{i}", disco, holder=holders[i],
                         replica_n=replica_n,
                         heartbeat_interval=hb).open()
             for i in range(n)]
    return disco, holders, nodes


def _close_all(nodes):
    for nd in nodes:
        try:
            nd.close()
        except Exception:
            pass


def _seed(nodes, n_shards=4, per_shard=8):
    nodes[0].apply_schema(SCHEMA)
    rows, cols = [], []
    for s in range(n_shards):
        for i in range(per_shard):
            rows.append(1)
            cols.append(s * SHARD + i * 31)
    nodes[0].import_bits("c", "f", rows, cols)


def _req(port, method, path, body=None, headers=None):
    import http.client
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    data = json.dumps(body) if isinstance(body, (dict, list)) else body
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    c.request(method, path, body=data, headers=hdrs)
    r = c.getresponse()
    raw = r.read()
    c.close()
    try:
        return r.status, json.loads(raw)
    except json.JSONDecodeError:
        return r.status, raw.decode()


def _cluster_rec():
    return next(r for r in flight.recorder.recent(50)
                if r.get("route") == "cluster")


# ---------------------------------------------------------------------------
# cross-node trace propagation
# ---------------------------------------------------------------------------

def test_cross_node_trace_propagation(hedge_off):
    disco, _holders, nodes = _mk_cluster()
    try:
        _seed(nodes)
        flight.recorder.clear()
        out = nodes[0].query("c", "Count(Row(f=1))")
        assert out["results"] == [32]
        rec = _cluster_rec()
        # every leg's span tree came home: the remote nodes' trees
        # rode the response trailer, the local leg recorded in place
        lanes = {e["node"] for e in rec.get("node_spans", ())}
        assert len(lanes) >= 2, rec.get("node_spans")
        remote = [e for e in rec["node_spans"]
                  if e["node"] != "node0"]
        assert remote, "no remote span tree came back"
        root = remote[0]["spans"][0]
        # the remote wrapped its execution in one rpc span whose
        # children are the engine's own spans
        assert root["name"].startswith("rpc:")
        child_names = [c["name"] for c in root.get("children", ())]
        assert "executor.Execute" in child_names
        assert root.get("tags", {}).get("node") == remote[0]["node"]
        # remote legs' own flight records inherited the trace id —
        # the merge key for /debug/cluster/queries
        same = [r for r in flight.recorder.recent(50)
                if r.get("trace_id") == rec["trace_id"]]
        assert len(same) >= 2
        assert any(r.get("inherited") for r in same)
    finally:
        _close_all(nodes)


def test_response_carries_no_trace_without_header(hedge_off):
    """A plain client query must not pay (or see) span serialization
    — the trailer only exists when the caller asked via header."""
    disco, _holders, nodes = _mk_cluster(n=1, replica_n=1)
    try:
        nodes[0].apply_schema(SCHEMA)
        nodes[0].api.query("c", "Set(1, f=1)")
        st, d = _req(nodes[0].server.port, "POST", "/index/c/query",
                     {"query": "Count(Row(f=1))"})
        assert st == 200 and "trace" not in d
        st, d = _req(nodes[0].server.port, "POST", "/index/c/query",
                     {"query": "Count(Row(f=1))", "remote": True},
                     headers={"X-Pilosa-Trace-Id": "qcanary",
                              "X-Pilosa-Span-Parent": "exec"})
        assert st == 200 and d["trace"]["spans"]
        assert d["trace"]["spans"][0]["tags"]["parent"] == "exec"
        # the remote-leg record joined the caller's trace id
        rec = next(r for r in flight.recorder.recent(20)
                   if r.get("trace_id") == "qcanary")
        assert rec.get("inherited") is True
    finally:
        _close_all(nodes)


# ---------------------------------------------------------------------------
# acceptance: hedged read renders as per-node lanes under one trace id
# ---------------------------------------------------------------------------

def test_hedged_query_one_timeline_with_node_lanes(monkeypatch):
    """ISSUE 10 acceptance: an in-process 3-node cluster serves one
    query with a hedged replica read; the coordinator's /debug/trace
    carries spans from >=2 nodes under one trace id, and
    /debug/cluster/queries returns the merged flight record with the
    per-node attempts."""
    disco, _holders, nodes = _mk_cluster()
    try:
        _seed(nodes)
        monkeypatch.setenv("PILOSA_TPU_CLUSTER_HEDGE_MS", "-1")
        expect = nodes[0].query("c", "Count(Row(f=1))")["results"]
        # stall every RPC to node1 2s; hedge fires at a fixed 25ms
        faults.inject("rpc-delay", match=nodes[1].uri, times=0,
                      delay_s=2.0)
        monkeypatch.setenv("PILOSA_TPU_CLUSTER_HEDGE_MS", "25")
        won0 = metrics.CLUSTER_EVENTS.value(event="hedge_won")
        flight.recorder.clear()
        r = nodes[0].query("c", "Count(Row(f=1))")
        assert r["results"] == expect
        assert metrics.CLUSTER_EVENTS.value(event="hedge_won") > won0
        rec = _cluster_rec()
        tid = rec["trace_id"]
        # the hedge attempt is visible with its true start offset —
        # the "parallel span" the Perfetto lane renders
        assert any(a["outcome"].startswith("hedge")
                   or a["t_off_ms"] > 0 for a in rec["attempts"])

        # coordinator's /debug/trace: one timeline, >=2 node lanes
        st, trace = _req(nodes[0].server.port, "GET",
                         "/debug/trace?n=50")
        assert st == 200
        evs = trace["traceEvents"]
        lane_name = {e["pid"]: e["args"]["name"] for e in evs
                     if e.get("ph") == "M"}
        node_pids = {e["pid"] for e in evs
                     if e.get("ph") == "X" and e.get("tid") == tid
                     and e.get("cat") in ("node", "attempt")}
        lane_nodes = {lane_name.get(p) for p in node_pids}
        assert len(lane_nodes) >= 2, (lane_nodes, node_pids)

        # federated /debug/cluster/queries: ONE merged entry for the
        # trace id, per-node attempts on its spine.  The rpc-delay
        # fault sits on node1's whole uri — disarm it so the
        # federation fan-out itself isn't the thing being stalled
        faults.clear()
        st, fed = _req(nodes[0].server.port, "GET",
                       f"/debug/cluster/queries?trace_id={tid}")
        assert st == 200 and fed["partial"] is False
        assert sorted(fed["nodes"]) == ["node0", "node1", "node2"]
        ent = next(e for e in fed["queries"]
                   if e["trace_id"] == tid)
        assert ent["attempts"], ent
        assert {a["node"] for a in ent["attempts"]} & \
            {"node0", "node1", "node2"}
        assert ent["nodes"], "merged entry lost its per-node records"
    finally:
        _close_all(nodes)


# ---------------------------------------------------------------------------
# federation: per-node timeouts + partial flagging
# ---------------------------------------------------------------------------

def test_federated_queries_flags_dead_node_partial(hedge_off):
    disco, _holders, nodes = _mk_cluster()
    try:
        _seed(nodes)
        nodes[0].query("c", "Count(Row(f=1))")
        nodes[2].pause()  # socket gone: refused, not hung
        st, fed = _req(nodes[0].server.port, "GET",
                       "/debug/cluster/queries?timeout_ms=500")
        assert st == 200
        assert fed["partial"] is True
        assert fed["unreachable"] == ["node2"]
        assert "node0" in fed["nodes"] and "node1" in fed["nodes"]
        assert fed["queries"], "live nodes' records still merge"
    finally:
        _close_all(nodes)


def test_federated_metrics_aggregate(hedge_off):
    disco, _holders, nodes = _mk_cluster(n=2, replica_n=1)
    try:
        _seed(nodes, n_shards=2)
        nodes[0].query("c", "Count(Row(f=1))")
        st, fed = _req(nodes[0].server.port, "GET",
                       "/debug/cluster/metrics")
        assert st == 200 and fed["partial"] is False
        agg = fed["aggregate"]
        assert "pilosa_query_total" in agg
        # histograms merge as {count, sum}
        hist = agg["pilosa_query_duration_seconds"]
        ent = next(iter(hist.values()))
        assert set(ent) == {"count", "sum"} and ent["count"] > 0
        assert set(fed["per_node"]) == {"node0", "node1"}
    finally:
        _close_all(nodes)


# ---------------------------------------------------------------------------
# gRPC twin: trace-id metadata -> trace-json trailing metadata
# ---------------------------------------------------------------------------

def test_grpc_trace_metadata():
    grpc = pytest.importorskip("grpc")
    from pilosa_tpu.api import API
    from pilosa_tpu.server.grpc import GRPCServer
    from pilosa_tpu.server.proto import pb

    holder = Holder()
    api = API(holder)
    srv = GRPCServer(api, bind="127.0.0.1:0").start()
    chan = grpc.insecure_channel(srv.uri)
    try:
        api.create_index("g")
        api.create_field("g", "f", {"type": "set"})
        api.query("g", "Set(1, f=7)")
        fn = chan.unary_unary(
            "/proto.Pilosa/QueryPQLUnary",
            request_serializer=pb.QueryPQLRequest.SerializeToString,
            response_deserializer=pb.TableResponse.FromString)
        flight.recorder.clear()
        resp, call = fn.with_call(
            pb.QueryPQLRequest(index="g", pql="Count(Row(f=7))"),
            metadata=(("trace-id", "qgrpc1"),))
        assert resp.rows[0].columns[0].uint64Val == 1
        md = dict(call.trailing_metadata() or ())
        tr = json.loads(md["trace-json"])
        assert tr["spans"] and tr["spans"][0]["name"] == \
            "executor.Execute"
        rec = next(r for r in flight.recorder.recent(20)
                   if r.get("trace_id") == "qgrpc1")
        assert rec.get("inherited") is True
        # without the metadata no trailer rides along
        _resp, call = fn.with_call(
            pb.QueryPQLRequest(index="g", pql="Count(Row(f=7))"))
        assert "trace-json" not in dict(call.trailing_metadata() or ())
    finally:
        chan.close()
        srv.stop()
        holder.close()


# ---------------------------------------------------------------------------
# attempts render with true start offsets (parallel, not stacked)
# ---------------------------------------------------------------------------

def test_attempt_offsets_monotone_in_record(hedge_off):
    disco, _holders, nodes = _mk_cluster(n=2, replica_n=1)
    try:
        _seed(nodes, n_shards=2)
        flight.recorder.clear()
        nodes[0].query("c", "Count(Row(f=1))")
        rec = _cluster_rec()
        for a in rec["attempts"]:
            assert a["t_off_ms"] >= 0
            assert a["ms"] >= 0
        # the chrome export places each attempt at start offset
        doc = flight.recorder.chrome_trace(20)
        att = [e for e in doc["traceEvents"]
               if e.get("cat") == "attempt"
               and e.get("tid") == rec["trace_id"]]
        assert att, "attempts missing from the chrome export"
        q = next(e for e in doc["traceEvents"]
                 if e.get("cat") == "query"
                 and e["tid"] == rec["trace_id"])
        assert all(e["ts"] >= q["ts"] for e in att)
    finally:
        _close_all(nodes)
