"""Statistics catalog (ISSUE 12): persistence round-trip + torn-tail
+ crash-mid-snapshot, the regression sentinel fire/clear cycle,
stats-fed cost decisions (admission classing, cost gates, cache
eviction, hedge derivation) with the PILOSA_TPU_STATS=0 kill-switch
as the bit-exact A/B lever, and warm post-restart planning."""

import json
import os

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.obs import faults, flight, metrics, stats


@pytest.fixture()
def catalog(tmp_path):
    """A fresh persisted catalog installed as the process global,
    restored (and the env kill-switch cleared) afterwards."""
    cat = stats.StatsCatalog(path=str(tmp_path / "stats.jsonl"),
                             regression_min_samples=6)
    prev = stats.swap(cat)
    prev_env = os.environ.pop("PILOSA_TPU_STATS", None)
    prev_enabled = stats._enabled
    stats._enabled = None
    yield cat
    stats._enabled = prev_enabled
    if prev_env is not None:
        os.environ["PILOSA_TPU_STATS"] = prev_env
    cat.close()
    stats.swap(prev)
    faults.clear()


def _mini_api(shards=2):
    h = Holder()
    api = API(h)
    api.create_index("si")
    api.create_field("si", "f", {"type": "set"})
    api.create_field("si", "g", {"type": "set"})
    rows, cols = [], []
    for s in range(shards):
        for c in range(64):
            rows.append(c % 3)
            cols.append(s * h.width + c)
    api.import_bits("si", "f", rows=rows, cols=cols)
    api.import_bits("si", "g", rows=[r + 10 for r in rows], cols=cols)
    return api


def _cluster_rec(node, ms):
    return {"trace_id": "t", "route": "cluster", "duration_ms": ms,
            "start": 0.0, "batch": 1, "phases": {}, "bytes_moved": 0,
            "attempts": [{"node": node, "ms": ms, "outcome": "ok",
                          "t_off_ms": 0.0}]}


# ---------------------------------------------------------------------------
# persistence
# ---------------------------------------------------------------------------

def test_snapshot_round_trip(catalog, tmp_path):
    """Full-state snapshot round-trip: data stats, profiles, node
    attempts, and gate rates all survive a 'restart' (fresh catalog
    over the same path)."""
    catalog.note_ingest("si", "f", rows=[0, 1, 2],
                        cols=[1, 2, 1 << 20], width=1 << 20)
    for i in range(8):
        catalog.note_flight({"fingerprint": "fp1", "route": "direct",
                             "duration_ms": 2.0 + i * 0.1,
                             "phases": {"execute": 1.0}, "batch": 1,
                             "bytes_moved": 100})
    for _ in range(40):
        catalog.note_flight(_cluster_rec("n1", 10.0))
        catalog.note_flight(_cluster_rec("n2", 40.0))
    catalog.note_gate("groupby_onepass", 1000.0, 0.002)
    catalog.save()

    cat2 = stats.StatsCatalog(path=str(tmp_path / "stats.jsonl"))
    assert cat2.loaded_from_disk
    assert cat2.field_stats("si", "f")["rows"] == 3
    assert cat2.field_stats("si", "f")["shards"] == 2
    p = cat2.profile("fp1")
    assert p is not None and p.n == 8
    assert abs(p.ms - catalog.profile("fp1").ms) < 1e-9
    assert cat2.hedge_samples() is not None
    assert cat2._gate_rates["groupby_onepass"][1] == 1
    cat2.close()


def test_tail_replay_and_torn_tail_recompact(catalog, tmp_path):
    """Ingest events land in the tail log; a torn final line (crash
    mid-append) is dropped on restart and the store recompacts
    immediately — the next load replays no tail at all."""
    catalog.note_ingest("si", "f", rows=[0], cols=[5], width=1 << 20)
    catalog.note_ingest("si", "f", rows=[1], cols=[6], width=1 << 20)
    path = tmp_path / "stats.jsonl"
    # simulate the crash: append half an event line
    with open(path, "a") as f:
        f.write('{"t": "ingest", "i": "si", "f": "f", "ro')
    cat2 = stats.StatsCatalog(path=str(path))
    fs = cat2.field_stats("si", "f")
    assert fs["rows"] == 2  # the torn third event is dropped
    # immediate recompaction: tail truncated, snapshot holds the state
    assert cat2.store.tail_records == 0
    assert os.path.getsize(path) == 0
    with open(str(path) + ".snap") as f:
        snap = json.load(f)
    assert snap["fields"]
    cat2.close()
    # a third open serves the same state from the snapshot alone
    cat3 = stats.StatsCatalog(path=str(path))
    assert cat3.field_stats("si", "f")["rows"] == 2
    cat3.close()


def test_crash_mid_snapshot_never_serves_half_file(catalog, tmp_path):
    """The stats-snapshot fault point crashes the compactor mid-write:
    the tmp file is torn, the rename never happens, and the catalog
    keeps serving the previous complete snapshot."""
    catalog.note_ingest("si", "f", rows=[0, 1], cols=[1, 2],
                        width=1 << 20)
    catalog.save()  # good snapshot with 2 rows
    catalog.note_ingest("si", "f", rows=[2], cols=[3], width=1 << 20)
    faults.inject("stats-snapshot", times=1)
    with pytest.raises(faults.InjectedFault):
        catalog.save()
    path = tmp_path / "stats.jsonl"
    # the torn tmp is left behind; the real snapshot is the old one
    with open(str(path) + ".snap") as f:
        snap = json.load(f)  # parses: complete, not torn
    cat2 = stats.StatsCatalog(path=str(path))
    # 2 rows from the intact snapshot + the third from the tail log
    # (appended before the crash) — nothing lost, nothing half-read
    assert cat2.field_stats("si", "f")["rows"] == 3
    assert snap["fields"]
    cat2.close()


def test_corrupt_store_fails_open(catalog, tmp_path):
    """Externally corrupted stats files must never refuse a boot:
    a corrupt snapshot loads as empty, a corrupt NON-final tail line
    is dropped (the rest replays) and the store recompacts — stats
    are advisory telemetry, not correctness state."""
    catalog.note_ingest("si", "f", rows=[0], cols=[5], width=1 << 20)
    catalog.note_ingest("si", "f", rows=[1], cols=[6], width=1 << 20)
    catalog.save()
    catalog.note_ingest("si", "f", rows=[2], cols=[7], width=1 << 20)
    path = tmp_path / "stats.jsonl"
    # corrupt the snapshot AND wedge a garbage line mid-tail
    with open(str(path) + ".snap", "r+") as f:
        f.seek(5)
        f.write("\x00GARBAGE")
    with open(path) as f:
        good_tail = f.read()
    with open(path, "w") as f:
        f.write("{not json at all\n" + good_tail)
    cat2 = stats.StatsCatalog(path=str(path))   # must not raise
    # snapshot state lost (corrupt), surviving tail event replayed
    assert cat2.field_stats("si", "f")["bits"] == 1
    # recompacted: a third open serves the same without the damage
    cat3 = stats.StatsCatalog(path=str(path))
    assert cat3.field_stats("si", "f")["bits"] == 1
    cat3.close()
    cat2.close()


def test_snapshot_rename_crash_does_not_double_replay(catalog,
                                                      tmp_path):
    """A crash BETWEEN the snapshot rename and the tail truncation
    leaves the already-folded tail behind; the sequence watermark
    (_tail_seq / event \"q\") must keep the reload from replaying it
    on top of the snapshot — additive data stats would double."""
    catalog.note_ingest("si", "f", rows=[0], cols=[5], width=1 << 20)
    path = tmp_path / "stats.jsonl"
    with open(path) as f:
        stale_tail = f.read()  # the seq-1 event, pre-compaction
    catalog.save()  # snapshot folds it and truncates the tail
    # simulate the crash window: the old tail reappears untruncated
    with open(path, "w") as f:
        f.write(stale_tail)
    cat2 = stats.StatsCatalog(path=str(path))
    assert cat2.field_stats("si", "f")["bits"] == 1  # not 2
    # and a NEW event after the reload still lands (fresh sequence)
    cat2.note_ingest("si", "f", rows=[1], cols=[6], width=1 << 20)
    assert cat2.field_stats("si", "f")["bits"] == 2
    cat2.close()


# ---------------------------------------------------------------------------
# regression sentinel
# ---------------------------------------------------------------------------

def test_sentinel_fires_on_injected_slowdown_and_clears(catalog):
    """A serving-dispatch delay fault slows one fingerprint:
    pilosa_perf_regression fires within the configured window (6
    samples here) and clears after recovery; the clean fingerprint
    stays silent throughout."""
    api = _mini_api()
    api.executor.enable_serving(ragged=False, cache_bytes=0)
    for _ in range(12):
        api.query("si", "Count(Row(f=0))")
        api.query("si", "Count(Row(g=10))")
    catalog.fold()
    fp = flight.recorder.recent(2)[0]["fingerprint"]
    assert not catalog.regressions()
    faults.inject("serving-dispatch", delay_s=0.03, times=12,
                  error=False)
    for _ in range(12):
        api.query("si", "Count(Row(f=0))")  # only THIS one slows
    catalog.fold()
    regs = catalog.regressions()
    assert len(regs) == 1
    slow_fp = regs[0]["fingerprint"]
    assert regs[0]["ratio"] >= catalog.regression_ratio
    assert metrics.PERF_REGRESSION.value(
        fingerprint=slow_fp, metric="duration_ms") > 0
    # recovery: the fault budget is exhausted; the window EWMA falls
    # back toward the (frozen) baseline and the sentinel clears
    for _ in range(16):
        api.query("si", "Count(Row(f=0))")
    catalog.fold()
    assert not catalog.regressions()
    assert metrics.PERF_REGRESSION.value(
        fingerprint=slow_fp, metric="duration_ms") == 0.0
    assert fp is not None  # both fingerprints were live


# ---------------------------------------------------------------------------
# stats-fed decisions: bit-exact A/B + behavior
# ---------------------------------------------------------------------------

_QUERIES = ("Count(Row(f=0))", "Row(f=1)",
            "GroupBy(Rows(field=f))",
            "TopN(f, n=2)",
            "Count(Intersect(Row(f=0), Row(g=10)))")


def test_stats_on_vs_off_bit_exact(catalog):
    """The kill-switch A/B: every query result is identical with the
    catalog enabled and disabled — stats steer plan and schedule
    choices only, never results."""
    api = _mini_api()
    api.executor.enable_serving(ragged=False)
    on = []
    for _ in range(3):
        on.extend(json.dumps(api.query("si", q), sort_keys=True,
                             default=str) for q in _QUERIES)
    os.environ["PILOSA_TPU_STATS"] = "0"
    try:
        assert not stats.enabled()
        off = []
        for _ in range(3):
            off.extend(json.dumps(api.query("si", q), sort_keys=True,
                                  default=str) for q in _QUERIES)
    finally:
        del os.environ["PILOSA_TPU_STATS"]
    assert on == off


def test_admission_classifies_by_measured_cost(catalog):
    """A kind-heavy query (GroupBy) whose measured cost is tiny rides
    the point lane once its profile is warm; the static fallback
    classes it heavy."""
    from pilosa_tpu.executor import sched
    from pilosa_tpu.executor.serving import _fingerprint
    from pilosa_tpu.pql import parse

    catalog.heavy_cost_ms = 5.0
    q = parse("GroupBy(Rows(field=f))")
    key = ("si", repr(q.calls), None)
    fp = _fingerprint(key)
    # cold: static kind walk says heavy
    assert sched.classify(q, None, fingerprint=fp) == sched.CLASS_HEAVY
    # warm a cheap profile
    for _ in range(6):
        catalog.note_flight({"fingerprint": fp, "route": "fused",
                             "duration_ms": 0.4, "phases": {},
                             "batch": 1, "bytes_moved": 0})
    catalog.fold()
    assert sched.classify(q, None, fingerprint=fp) == sched.CLASS_POINT
    # an expensive profile flips it back
    for _ in range(12):
        catalog.note_flight({"fingerprint": fp, "route": "direct",
                             "duration_ms": 80.0, "phases": {},
                             "batch": 1, "bytes_moved": 0})
    catalog.fold()
    assert sched.classify(q, None, fingerprint=fp) == sched.CLASS_HEAVY
    # explicit priority still outranks the profile
    qos = sched.QoS.make(priority="point")
    assert sched.classify(q, qos, fingerprint=fp) == sched.CLASS_POINT


def test_cache_hits_do_not_erode_recompute_estimate(catalog):
    """Serve-cost and recompute-cost are separate estimates: a run
    of sub-ms cache hits drags the admission estimate down (correct
    — serving a cached entry costs nothing) but must NOT touch the
    recompute estimate the cache's own eviction ranks by."""
    fp = "split-fp"
    for _ in range(4):
        catalog.note_flight({"fingerprint": fp, "route": "direct",
                             "duration_ms": 80.0, "phases": {},
                             "batch": 1, "bytes_moved": 0})
    for _ in range(40):
        catalog.note_flight({"fingerprint": fp, "route": "cached",
                             "duration_ms": 0.1, "phases": {},
                             "batch": 1, "bytes_moved": 0})
    catalog.fold()
    assert catalog.est_cost_ms(fp) < 5.0        # admission: cheap
    assert catalog.est_recompute_ms(fp) == 80.0  # eviction: honest


def test_gate_rate_outlier_and_staleness(catalog):
    """One compile-laden wall-time outlier folds with a damped alpha
    (cannot flip the gate), and an arm unsampled past the staleness
    window drops the pair back to the static model."""
    for _ in range(4):
        catalog.note_gate("a", 1000.0, 0.001)   # 1e-6 s/unit
        catalog.note_gate("b", 1000.0, 0.002)
    ra0, _ = catalog.gate_rates("a", "b")
    catalog.note_gate("a", 1000.0, 1.0)         # 1000x outlier
    ra1, _ = catalog.gate_rates("a", "b")
    assert ra1 < 100 * ra0  # damped, not EWMA(0.3)-absorbed
    # staleness: age arm "a" past the window -> static fallback
    with catalog._lock:
        r, n, _t = catalog._gate_rates["a"]
        catalog._gate_rates["a"] = (
            r, n, _t - catalog._GATE_STALE_S - 1)
    assert catalog.gate_rates("a", "b") == (1.0, 1.0)


def test_result_cache_eviction_prefers_high_cost(catalog):
    """Under byte pressure the cache evicts the cheapest-to-recompute
    entry among the LRU window, not blindly the oldest; with no costs
    (stats off) it stays pure LRU."""
    import numpy as np

    from pilosa_tpu.executor.serving import ResultCache

    def payload():
        return np.zeros(64, dtype=np.int64)  # 512 accounted bytes

    cache = ResultCache(max_bytes=2200)  # ~4 entries
    idx_keys = [("i", f"q{i}", None) for i in range(8)]
    # oldest entry is EXPENSIVE, the rest cheap
    cache.put(idx_keys[0], frozenset(), (), payload(), cost_ms=500.0)
    for k in idx_keys[1:]:
        cache.put(k, frozenset(), (), payload(), cost_ms=0.2)
    assert idx_keys[0] in cache          # survived despite being LRU
    assert idx_keys[1] not in cache      # a cheap one went instead
    # pure-LRU arm: no costs -> strict insertion-order eviction
    lru = ResultCache(max_bytes=2200)
    for k in idx_keys:
        lru.put(k, frozenset(), (), payload())
    assert idx_keys[0] not in lru
    assert idx_keys[-1] in lru


def test_groupby_gate_uses_measured_rates(catalog):
    """The one-pass-vs-per-combo gate flips when measured rates say
    the static unit model is wrong by a large factor — and the
    decision is identical after a catalog restart (warm planning)."""
    from pilosa_tpu.executor.stacked import _groupby_unit_costs

    api = _mini_api()
    idx = api.holder.index("si")
    eng = api.executor.stacked
    f = idx.field("f")
    fields_rows = [(f, [0, 1, 2])]
    skey = (0, 1)
    base = eng._groupby_onepass_ok(idx, fields_rows, 3, 0, False, skey)
    one_u, combo_u = _groupby_unit_costs(fields_rows, 3, 0, False,
                                         len(skey), idx.width // 32)
    # static model: tiny combo products stay per-combo
    assert base is False
    # measured: one-pass units are (falsely, for the test) 1000x
    # cheaper per unit than per-combo units -> the gate flips
    for _ in range(4):
        catalog.note_gate("groupby_onepass", one_u, one_u * 1e-9)
        catalog.note_gate("groupby_percombo", combo_u, combo_u * 1e-6)
    assert eng._groupby_onepass_ok(idx, fields_rows, 3, 0, False,
                                   skey) is True
    # persistence: a restarted catalog makes the SAME decision
    catalog.save()
    cat2 = stats.StatsCatalog(path=catalog.store.path)
    prev = stats.swap(cat2)
    try:
        assert eng._groupby_onepass_ok(idx, fields_rows, 3, 0, False,
                                       skey) is True
    finally:
        stats.swap(catalog)
        cat2.close()


def test_hedge_delay_from_persisted_stats(catalog, tmp_path):
    """Hedge-delay derivation reads the catalog's per-node attempt
    distributions — and a freshly restarted catalog derives the SAME
    delay (no cold-start default window)."""
    from pilosa_tpu.cluster.coordinator import derive_hedge_delay_s

    flight.recorder.clear()
    # without stats samples and an empty ring: the cold default
    assert derive_hedge_delay_s(default_s=0.05) == 0.05
    for i in range(40):
        catalog.note_flight(_cluster_rec("fastnode", 8.0 + (i % 5)))
        catalog.note_flight(_cluster_rec("slownode", 200.0))
    catalog.fold()
    warm = derive_hedge_delay_s(default_s=0.05)
    # anchored to the healthy replica, not the 200 ms one
    assert 0.005 <= warm <= 0.02
    catalog.save()
    cat2 = stats.StatsCatalog(path=str(tmp_path / "stats.jsonl"))
    prev = stats.swap(cat2)
    try:
        assert derive_hedge_delay_s(default_s=0.05) == warm
    finally:
        stats.swap(catalog)
        cat2.close()


def test_patch_break_even_requires_volume(catalog, monkeypatch):
    """The measured patch-vs-rebuild threshold stays None (static
    fallback) until both arms have real byte volume, then equals the
    measured per-byte-cost ratio (injected readings — the real
    counters are process-cumulative)."""
    vols = {"patched": 0.0, "rebuilt": 0.0}
    sums = {"stack_patch": 0.0, "stack_rebuild": 0.0}
    monkeypatch.setattr(metrics.STACK_MAINT_BYTES, "value",
                        lambda **kw: vols[kw["kind"]])
    monkeypatch.setattr(metrics.PHASE_DURATION, "sum",
                        lambda **kw: sums[kw["phase"]])
    catalog._patch_memo = None
    assert catalog.patch_break_even_frac() is None
    vols.update(patched=float(4 << 20), rebuilt=float(8 << 20))
    sums.update(stack_patch=0.2, stack_rebuild=0.1)
    catalog._patch_memo = None  # drop the 1s memo
    f = catalog.patch_break_even_frac()
    # c_patch = 0.2s/4MiB, c_rebuild = 0.1s/8MiB -> break-even 0.25
    assert f is not None and abs(f - 0.25) < 1e-9


# ---------------------------------------------------------------------------
# warm restart end to end + /debug/stats
# ---------------------------------------------------------------------------

def test_restarted_server_serves_reloaded_catalog(catalog, tmp_path):
    """/debug/stats on a restarted server serves the reloaded
    catalog: profiles and data stats from the previous 'life' are
    present before any query runs."""
    import http.client

    from pilosa_tpu.server.http import Server

    api = _mini_api()
    api.executor.enable_serving(ragged=False)
    for _ in range(8):
        api.query("si", "Count(Row(f=0))")
    catalog.fold()
    assert catalog.payload()["runtime"]
    catalog.save()

    # 'restart': a fresh catalog over the same path behind a server
    cat2 = stats.StatsCatalog(path=str(tmp_path / "stats.jsonl"))
    stats.swap(cat2)
    try:
        srv = Server().start()
        try:
            c = http.client.HTTPConnection("127.0.0.1", srv.port,
                                           timeout=10)
            c.request("GET", "/debug/stats")
            body = json.loads(c.getresponse().read())
            c.close()
        finally:
            srv.close()
        assert body["enabled"] is True
        assert body["runtime"], "reloaded profiles must be served"
        assert body["data"].get("si/f", {}).get("rows", 0) > 0
        assert body["store"]["loaded"] is True
    finally:
        stats.swap(catalog)
        cat2.close()
