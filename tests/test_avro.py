"""Avro + schema-registry Kafka source tests (idk/kafka/source.go:34)
and exactly-once id allocation through the pipeline (idalloc.go:127)."""

from decimal import Decimal

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.ingest import avro
from pilosa_tpu.ingest.avro import (
    AvroError,
    AvroStreamSource,
    SchemaRegistry,
)
from pilosa_tpu.ingest.importer import APIImporter
from pilosa_tpu.ingest.kafka import Broker, StreamSource
from pilosa_tpu.ingest.pipeline import Pipeline
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.storage.idalloc import IDAllocator

SCHEMA = {
    "type": "record", "name": "ev", "fields": [
        {"name": "_id", "type": "long"},
        {"name": "lvl", "type": "string"},
        {"name": "code", "type": "long"},
        {"name": "ok", "type": "boolean"},
        {"name": "score", "type": "double"},
        {"name": "amount", "type": {"type": "bytes",
                                    "logicalType": "decimal",
                                    "scale": 2}},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
        {"name": "note", "type": ["null", "string"]},
    ]}


class TestCodec:
    CASES = [
        {"_id": 1, "lvl": "err", "code": 7, "ok": True,
         "score": 1.5, "amount": Decimal("12.34"),
         "tags": ["a", "b"], "note": "hi"},
        {"_id": 2 ** 40, "lvl": "", "code": -3, "ok": False,
         "score": -0.25, "amount": Decimal("-0.05"),
         "tags": [], "note": None},
    ]

    def test_roundtrip(self):
        for obj in self.CASES:
            raw = avro.encode(SCHEMA, obj)
            got = avro.decode(SCHEMA, raw)
            assert got == obj, (got, obj)

    def test_wire_frame(self):
        body = avro.encode(SCHEMA, self.CASES[0])
        framed = avro.frame(42, body)
        sid, got = avro.unframe(framed)
        assert sid == 42 and got == body
        with pytest.raises(AvroError):
            avro.unframe(b"\x01xxxx")

    def test_varint_edges(self):
        s = {"type": "record", "name": "r",
             "fields": [{"name": "v", "type": "long"}]}
        for v in (0, -1, 1, 63, 64, -64, -65, 2**62, -(2**62)):
            assert avro.decode(s, avro.encode(s, {"v": v})) == {"v": v}


def _produce(broker, registry, objs, schema=SCHEMA,
             subject="ev-value", topic="ev"):
    sid = registry.register(subject, schema)
    for o in objs:
        broker.produce(topic, avro.frame(sid, avro.encode(schema, o)),
                       key=str(o.get("_id")))


def test_avro_source_through_pipeline():
    """Fake registry + Confluent-framed messages -> records -> a real
    index; the pilosa schema derives from the Avro schema."""
    b, reg = Broker(), SchemaRegistry()
    objs = [{"_id": i, "lvl": "err" if i % 5 == 0 else "info",
             "code": i % 4, "ok": i % 2 == 0,
             "score": i / 8, "amount": Decimal(i).scaleb(-2),
             "tags": ["t%d" % (i % 3)], "note": None}
            for i in range(40)]
    _produce(b, reg, objs)
    api = API(Holder())
    src = AvroStreamSource(b, "ev", reg, group="g")
    pipe = Pipeline(src, APIImporter(api), "ev")
    # schema comes from the registry schema at first decode
    for _ in src:
        break
    assert src.schema["lvl"] == {"type": "set", "keys": True}
    assert src.schema["amount"]["type"] == "decimal"
    assert src.schema["amount"]["scale"] == 2
    n = pipe.run()
    assert n >= 39
    r = api.sql("SELECT count(*) FROM ev WHERE lvl = 'err'")
    assert r["data"][0][0] == 8
    r = api.sql("SELECT count(*) FROM ev WHERE ok = true")
    assert r["data"][0][0] == 20
    r = api.sql("SELECT sum(amount) FROM ev")
    assert r["data"][0][0] == float(sum(Decimal(i).scaleb(-2)
                                        for i in range(40)))


def test_avro_schema_evolution_mid_stream():
    """A new registered schema version applies to later messages
    (registry-driven refresh, like idk's schema-registry client)."""
    b, reg = Broker(), SchemaRegistry()
    v1 = {"type": "record", "name": "ev", "fields": [
        {"name": "_id", "type": "long"},
        {"name": "a", "type": "long"}]}
    v2 = {"type": "record", "name": "ev", "fields": [
        {"name": "_id", "type": "long"},
        {"name": "a", "type": "long"},
        {"name": "b", "type": "string"}]}
    _produce(b, reg, [{"_id": 1, "a": 5}], schema=v1)
    _produce(b, reg, [{"_id": 2, "a": 6, "b": "x"}], schema=v2)
    src = AvroStreamSource(b, "ev", reg, group="g")
    recs = list(src)
    assert len(recs) == 2
    assert "b" in src.schema  # evolved field detected
    by_id = {r.id: r.values for r in recs}
    assert by_id[2]["b"] == "x" and "b" not in by_id[1]


def test_pipeline_exactly_once_ids_on_retry():
    """Records without _id get allocator ids; a crashed batch retried
    from uncommitted offsets reserves the SAME session and therefore
    the same ids (idalloc.go:127) — no duplicate records."""
    schema = {"type": "record", "name": "ev", "fields": [
        {"name": "val", "type": "long"}]}
    b, reg = Broker(n_partitions=1), SchemaRegistry()
    sid = reg.register("ev-value", schema)
    for i in range(6):
        b.produce("ev", avro.frame(sid, avro.encode(
            schema, {"val": i})), partition=0)

    alloc = IDAllocator()
    api = API(Holder())

    class CrashImporter(APIImporter):
        """Fails the FIRST flush after records landed — after ids were
        reserved but before offsets commit (the crash window)."""
        def __init__(self, api):
            super().__init__(api)
            self.crashed = False

        def import_values(self, *a, **kw):
            if not self.crashed:
                self.crashed = True
                raise ConnectionError("importer crashed mid-flush")
            return super().import_values(*a, **kw)

    imp = CrashImporter(api)
    src = AvroStreamSource(b, "ev", reg, group="g")
    pipe = Pipeline(src, imp, "ev", batch_size=3, allocator=alloc)
    with pytest.raises(ConnectionError):
        pipe.run()

    # retry: offsets were never committed -> full re-delivery; the
    # same sessions reserve the same ranges -> identical ids
    src2 = AvroStreamSource(b, "ev", reg, group="g")
    pipe2 = Pipeline(src2, imp, "ev", batch_size=3, allocator=alloc)
    n = pipe2.run()
    assert n == 6
    r = api.sql("SELECT count(*) FROM ev")
    assert r["data"][0][0] == 6  # no duplicates from the retry
    r = api.sql("SELECT count(distinct val) FROM ev")
    assert r["data"][0][0] == 6
