"""Pallas kernels vs the jnp reference ops and numpy naive impls.

Runs in interpreter mode on the CPU test mesh (kernels auto-select
interpret off-TPU), mirroring the reference's kernel-vs-naive
cross-checks (roaring/naive.go:309).
"""

import numpy as np
import pytest

from pilosa_tpu.ops import bitmap as bm
from pilosa_tpu.ops import bsi
from pilosa_tpu.ops import kernels


def _rand_words(rng, shape, density=0.5):
    words = rng.integers(0, 2**32, size=shape, dtype=np.uint32)
    if density < 0.5:
        words &= rng.integers(0, 2**32, size=shape, dtype=np.uint32)
    return words


@pytest.mark.parametrize("n,w", [(1, 128), (7, 256), (16, 1024)])
def test_popcount_rows(rng, n, w):
    x = _rand_words(rng, (n, w))
    got = np.asarray(kernels.popcount_rows(x))
    want = np.bitwise_count(x).sum(axis=-1)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("n,w", [(3, 128), (8, 512), (13, 1024)])
def test_pair_popcount(rng, n, w):
    a = _rand_words(rng, (n, w))
    b = _rand_words(rng, (n, w))
    got = np.asarray(kernels.pair_popcount(a, b))
    want = np.bitwise_count(a & b).sum(axis=-1)
    np.testing.assert_array_equal(got, want)
    # agrees with the jnp reference path
    np.testing.assert_array_equal(
        got, np.asarray(bm.intersection_count(a, b)))


@pytest.mark.parametrize("n,w", [(5, 128), (32, 2048)])
def test_masked_popcount(rng, n, w):
    x = _rand_words(rng, (n, w))
    m = _rand_words(rng, (w,))
    got = np.asarray(kernels.masked_popcount(x, m))
    want = np.bitwise_count(x & m[None]).sum(axis=-1)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("depth,w,filtered", [
    (1, 128, False), (7, 4096, True), (13, 8192, True), (33, 128, False),
])
def test_bsi_sum_counts_kernel(rng, depth, w, filtered):
    width = w * 32
    n = min(width // 2, 3000)
    cols = rng.choice(width, size=n, replace=False)
    vals = rng.integers(-(2**depth) + 1, 2**depth, size=n)
    planes = bsi.encode(cols, vals, depth=depth, width=width)
    filt = _rand_words(rng, (w,)) if filtered else None

    cnt, pos, neg = kernels.bsi_sum_counts(planes, filt)
    total, count = bsi.host_sum(cnt, pos, neg)

    rc, rpos, rneg = bsi.sum_counts(planes, filt)
    rtotal, rcount = bsi.host_sum(rc, rpos, rneg)
    assert (total, count) == (rtotal, rcount)

    # and against exact numpy ground truth
    if filtered:
        mask_bits = bm.to_columns(filt)
        sel = np.isin(cols, mask_bits)
    else:
        sel = np.ones(n, dtype=bool)
    assert count == int(sel.sum())
    assert total == int(vals[sel].sum())


# r=37 exercises host-side R chunking; w=192 a non-multiple word width
@pytest.mark.parametrize("s_dim,w,r", [(4, 256, 6), (9, 192, 37)])
def test_fused_query_counts(rng, s_dim, w, r):
    a = _rand_words(rng, (s_dim, w))
    b = _rand_words(rng, (s_dim, w))
    filt = _rand_words(rng, (s_dim, w))
    rows = _rand_words(rng, (r, s_dim, w))
    ci, rc = kernels.fused_query_counts(a, b, filt, rows)
    np.testing.assert_array_equal(
        np.asarray(ci), np.bitwise_count(a & b).sum(axis=-1))
    want_rc = np.bitwise_count(rows & filt[None]).sum(axis=-1)
    np.testing.assert_array_equal(np.asarray(rc), want_rc)


def test_bsi_sum_counts_nonmultiple_width(rng):
    # word width not a multiple of the 4096-word block: padding path
    w = 6144
    planes = _rand_words(rng, (5, w))
    filt = _rand_words(rng, (w,))
    got = kernels.bsi_sum_counts(planes, filt)
    from pilosa_tpu.ops import bsi as bsi_ops
    want = bsi_ops.sum_counts(planes, filt)
    assert int(got[0]) == int(want[0])
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))
    np.testing.assert_array_equal(np.asarray(got[2]), np.asarray(want[2]))


def test_kernels_under_jit(rng):
    """Kernels compose under jax.jit like any other jax op."""
    import jax

    a = _rand_words(rng, (8, 512))
    b = _rand_words(rng, (8, 512))

    @jax.jit
    def f(a, b):
        return kernels.pair_popcount(a, b)

    np.testing.assert_array_equal(
        np.asarray(f(a, b)), np.bitwise_count(a & b).sum(axis=-1))
