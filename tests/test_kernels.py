"""Pallas kernels vs the jnp reference ops and numpy naive impls.

Runs in interpreter mode on the CPU test mesh (kernels auto-select
interpret off-TPU), mirroring the reference's kernel-vs-naive
cross-checks (roaring/naive.go:309).
"""

import numpy as np
import pytest

from pilosa_tpu.ops import bitmap as bm
from pilosa_tpu.ops import bsi
from pilosa_tpu.ops import kernels


def _rand_words(rng, shape, density=0.5):
    words = rng.integers(0, 2**32, size=shape, dtype=np.uint32)
    if density < 0.5:
        words &= rng.integers(0, 2**32, size=shape, dtype=np.uint32)
    return words


@pytest.mark.parametrize("n,w", [(1, 128), (7, 256), (16, 1024)])
def test_popcount_rows(rng, n, w):
    x = _rand_words(rng, (n, w))
    got = np.asarray(kernels.popcount_rows(x))
    want = np.bitwise_count(x).sum(axis=-1)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("n,w", [(3, 128), (8, 512), (13, 1024)])
def test_pair_popcount(rng, n, w):
    a = _rand_words(rng, (n, w))
    b = _rand_words(rng, (n, w))
    got = np.asarray(kernels.pair_popcount(a, b))
    want = np.bitwise_count(a & b).sum(axis=-1)
    np.testing.assert_array_equal(got, want)
    # agrees with the jnp reference path
    np.testing.assert_array_equal(
        got, np.asarray(bm.intersection_count(a, b)))


@pytest.mark.parametrize("n,w", [(5, 128), (32, 2048)])
def test_masked_popcount(rng, n, w):
    x = _rand_words(rng, (n, w))
    m = _rand_words(rng, (w,))
    got = np.asarray(kernels.masked_popcount(x, m))
    want = np.bitwise_count(x & m[None]).sum(axis=-1)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("depth,w,filtered", [
    (1, 128, False), (7, 4096, True), (13, 8192, True), (33, 128, False),
])
def test_bsi_sum_counts_kernel(rng, depth, w, filtered):
    width = w * 32
    n = min(width // 2, 3000)
    cols = rng.choice(width, size=n, replace=False)
    vals = rng.integers(-(2**depth) + 1, 2**depth, size=n)
    planes = bsi.encode(cols, vals, depth=depth, width=width)
    filt = _rand_words(rng, (w,)) if filtered else None

    cnt, pos, neg = kernels.bsi_sum_counts(planes, filt)
    total, count = bsi.host_sum(cnt, pos, neg)

    rc, rpos, rneg = bsi.sum_counts(planes, filt)
    rtotal, rcount = bsi.host_sum(rc, rpos, rneg)
    assert (total, count) == (rtotal, rcount)

    # and against exact numpy ground truth
    if filtered:
        mask_bits = bm.to_columns(filt)
        sel = np.isin(cols, mask_bits)
    else:
        sel = np.ones(n, dtype=bool)
    assert count == int(sel.sum())
    assert total == int(vals[sel].sum())


# r=37 exercises host-side R chunking; w=192 a non-multiple word width
@pytest.mark.parametrize("s_dim,w,r", [(4, 256, 6), (9, 192, 37)])
def test_fused_query_counts(rng, s_dim, w, r):
    a = _rand_words(rng, (s_dim, w))
    b = _rand_words(rng, (s_dim, w))
    filt = _rand_words(rng, (s_dim, w))
    rows = _rand_words(rng, (r, s_dim, w))
    ci, rc = kernels.fused_query_counts(a, b, filt, rows)
    np.testing.assert_array_equal(
        np.asarray(ci), np.bitwise_count(a & b).sum(axis=-1))
    want_rc = np.bitwise_count(rows & filt[None]).sum(axis=-1)
    np.testing.assert_array_equal(np.asarray(rc), want_rc)


def test_bsi_sum_counts_nonmultiple_width(rng):
    # word width not a multiple of the 4096-word block: padding path
    w = 6144
    planes = _rand_words(rng, (5, w))
    filt = _rand_words(rng, (w,))
    got = kernels.bsi_sum_counts(planes, filt)
    from pilosa_tpu.ops import bsi as bsi_ops
    want = bsi_ops.sum_counts(planes, filt)
    assert int(got[0]) == int(want[0])
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))
    np.testing.assert_array_equal(np.asarray(got[2]), np.asarray(want[2]))


def test_kernels_under_jit(rng):
    """Kernels compose under jax.jit like any other jax op."""
    import jax

    a = _rand_words(rng, (8, 512))
    b = _rand_words(rng, (8, 512))

    @jax.jit
    def f(a, b):
        return kernels.pair_popcount(a, b)

    np.testing.assert_array_equal(
        np.asarray(f(a, b)), np.bitwise_count(a & b).sum(axis=-1))


def test_executor_pallas_dispatch(rng, monkeypatch):
    """PILOSA_TPU_PALLAS=1 forces the executor hot paths through the
    Pallas kernels (interpret mode on CPU) — results must be identical
    to the jnp path."""
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "1")
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.models.schema import FieldOptions, FieldType
    from pilosa_tpu.executor.executor import Executor

    width = 1 << 12
    h = Holder(width=width)
    idx = h.create_index("p")
    fld = idx.create_field("f", FieldOptions(type=FieldType.SET))
    val = idx.create_field("v", FieldOptions(type=FieldType.INT,
                                             min=-1000, max=1000))
    cols = rng.choice(3 * width, size=300, replace=False)
    rows = rng.integers(0, 10, size=300)
    vals = rng.integers(-1000, 1000, size=300)
    fld.import_bits(rows, cols)
    val.import_values(cols, vals.tolist())
    idx.mark_columns_exist([int(c) for c in cols])
    ex = Executor(h)
    got_sum = ex.execute("p", "Sum(Row(f=1), field=v)")[0]
    sel = rows == 1
    assert got_sum.value == int(vals[sel].sum())
    assert got_sum.count == int(sel.sum())
    # filter as positional child => the masked_popcount kernel path
    got_top = ex.execute("p", "TopN(f, Row(f=1), n=3)")[0]
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "0")
    want_top = ex.execute("p", "TopN(f, Row(f=1), n=3)")[0]
    # columns are unique per row here, so only row 1 intersects its
    # own filter — the point is kernel/jnp agreement, not cardinality
    assert [(p.id, p.count) for p in got_top] == \
        [(p.id, p.count) for p in want_top]
    assert got_top and got_top[0].id == 1


class TestGroupbySum:
    """Fused GroupBy kernel vs a naive numpy evaluation."""

    def _data(self, rng, depth=4):
        import itertools
        import jax.numpy as jnp
        S, W = 3, 64
        stacks = [jnp.asarray(rng.integers(
            0, 1 << 32, size=(r, S, W), dtype=np.uint32))
            for r in (4, 2)]
        planes = rng.integers(0, 1 << 32, size=(S, 2 + depth, W),
                              dtype=np.uint32)
        combos = np.array(list(itertools.product(range(4), range(2))),
                          dtype=np.int32)
        return stacks, planes, combos

    def test_matches_naive(self, rng):
        from pilosa_tpu.ops import kernels
        stacks, planes, combos = self._data(rng)
        depth = planes.shape[1] - 2
        counts, nn, pos, neg = kernels.groupby_sum(
            stacks, combos, planes, signed=True)
        for ci, (a, b) in enumerate(combos):
            m = np.asarray(stacks[0])[a] & np.asarray(stacks[1])[b]
            em = m & planes[:, 0]
            p_, g_ = em & ~planes[:, 1], em & planes[:, 1]
            assert int(counts[ci]) == int(np.bitwise_count(m).sum())
            assert int(nn[ci]) == int(np.bitwise_count(em).sum())
            assert [int(x) for x in pos[ci]] == [
                int(np.bitwise_count(p_ & planes[:, 2 + i]).sum())
                for i in range(depth)]
            assert [int(x) for x in neg[ci]] == [
                int(np.bitwise_count(g_ & planes[:, 2 + i]).sum())
                for i in range(depth)]

    def test_counts_only(self, rng):
        from pilosa_tpu.ops import kernels
        stacks, _planes, combos = self._data(rng)
        counts, nn, pos, neg = kernels.groupby_sum(stacks, combos, None)
        assert nn is None and pos is None and neg is None
        a, b = combos[3]
        m = np.asarray(stacks[0])[a] & np.asarray(stacks[1])[b]
        assert int(counts[3]) == int(np.bitwise_count(m).sum())

    def test_engine_groupby_kernel_path_matches_xla(
            self, rng, monkeypatch):
        """Force the kernel path (interpreter on CPU) through the REAL
        engine and compare to the default XLA scan."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models import FieldOptions, FieldType, Holder

        W = 1 << 12
        h = Holder(width=W)
        idx = h.create_index("i")
        idx.create_field("g")
        idx.create_field("d")
        idx.create_field("v", FieldOptions(type=FieldType.INT,
                                           min=-50, max=50))
        cols = list(range(0, 3 * W, 7))
        idx.field("g").import_bits([c % 3 for c in cols], cols)
        idx.field("d").import_bits([c % 2 for c in cols], cols)
        vals = [int(v) for v in rng.integers(-50, 50, size=len(cols))]
        idx.field("v").import_values(cols, vals)
        idx.mark_columns_exist(cols)
        q = "GroupBy(Rows(g), Rows(d), aggregate=Sum(field=v))"
        ex = Executor(h)
        want = ex.execute("i", q)[0]
        monkeypatch.setenv("PILOSA_TPU_GROUPBY_KERNEL", "1")
        got = Executor(h).execute("i", q)[0]
        as_t = lambda res: [(tuple(g["row_id"] for g in r.group),
                             r.count, r.agg, r.agg_count) for r in res]
        assert as_t(got) == as_t(want)

    def test_engine_groupby_kernel_on_mesh(self, rng, monkeypatch):
        """shard_map kernel path over a REAL 2x4 mesh: every device
        runs the fused kernel on its shard slice, partials psum."""
        import jax

        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models import FieldOptions, FieldType, Holder
        from pilosa_tpu.parallel.mesh import make_mesh

        if len(jax.devices()) < 8:
            import pytest
            pytest.skip("needs 8 devices")
        W = 1 << 12
        h = Holder(width=W)
        idx = h.create_index("i")
        idx.create_field("g")
        idx.create_field("d")
        idx.create_field("v", FieldOptions(type=FieldType.INT,
                                           min=-50, max=50))
        cols = list(range(0, 5 * W, 7))
        idx.field("g").import_bits([c % 3 for c in cols], cols)
        idx.field("d").import_bits([c % 2 for c in cols], cols)
        vals = [int(v) for v in rng.integers(-50, 50, size=len(cols))]
        idx.field("v").import_values(cols, vals)
        idx.mark_columns_exist(cols)
        q = "GroupBy(Rows(g), Rows(d), aggregate=Sum(field=v))"
        ex_loop = Executor(h)
        ex_loop.use_stacked = False
        want = ex_loop.execute("i", q)[0]
        monkeypatch.setenv("PILOSA_TPU_GROUPBY_KERNEL", "1")
        ex_mesh = Executor(h)
        ex_mesh.set_mesh(make_mesh(8, rows=2))
        got = ex_mesh.execute("i", q)[0]
        as_t = lambda res: [(tuple(g["row_id"] for g in r.group),
                             r.count, r.agg, r.agg_count) for r in res]
        assert as_t(got) == as_t(want)


class TestGroupByKernelGuardLifts:
    """r04 guard lifts: big combo spaces, big shard fleets, and
    filter trees all keep the kernel path (single device) — chunked
    and masked, results equal to the XLA scan."""

    def _holder(self, rng, W):
        from pilosa_tpu.models import FieldOptions, FieldType, Holder
        h = Holder(width=W)
        idx = h.create_index("i")
        idx.create_field("g")
        idx.create_field("d")
        idx.create_field("flt")
        idx.create_field("v", FieldOptions(type=FieldType.INT,
                                           min=-50, max=50))
        cols = list(range(0, 9 * W, 5))
        idx.field("g").import_bits([c % 5 for c in cols], cols)
        idx.field("d").import_bits([c % 4 for c in cols], cols)
        idx.field("flt").import_bits([c % 2 for c in cols], cols)
        vals = [int(v) for v in rng.integers(-50, 50,
                                             size=len(cols))]
        idx.field("v").import_values(cols, vals)
        idx.mark_columns_exist(cols)
        return h

    def _cmp(self, h, q, monkeypatch):
        from pilosa_tpu.executor import Executor
        monkeypatch.delenv("PILOSA_TPU_GROUPBY_KERNEL",
                           raising=False)
        want = Executor(h).execute("i", q)[0]
        monkeypatch.setenv("PILOSA_TPU_GROUPBY_KERNEL", "1")
        got = Executor(h).execute("i", q)[0]
        as_t = lambda res: [(tuple(g["row_id"] for g in r.group),
                             r.count, r.agg, r.agg_count)
                            for r in res]
        assert as_t(got) == as_t(want)

    def test_filter_tree_stays_on_kernel(self, rng, monkeypatch):
        h = self._holder(rng, 1 << 12)
        self._cmp(h, "GroupBy(Rows(g), Rows(d), filter=Row(flt=1), "
                     "aggregate=Sum(field=v))", monkeypatch)

    def test_combo_chunking_matches(self, rng, monkeypatch):
        import pilosa_tpu.executor.stacked as stacked
        monkeypatch.setattr(
            stacked.StackedEngine, "_GROUPBY_KERNEL_MAX_COMBOS", 3)
        h = self._holder(rng, 1 << 12)
        # 5 x 4 = 20 combos >> the patched 3-combo kernel bound
        self._cmp(h, "GroupBy(Rows(g), Rows(d), "
                     "aggregate=Sum(field=v))", monkeypatch)

    def test_shard_chunking_matches(self, rng, monkeypatch):
        import pilosa_tpu.executor.stacked as stacked
        monkeypatch.setattr(stacked, "_REDUCE_MAX_SHARDS", 2)
        h = self._holder(rng, 1 << 12)  # 9 shards >> patched bound
        self._cmp(h, "GroupBy(Rows(g), Rows(d), "
                     "aggregate=Sum(field=v))", monkeypatch)

    def test_all_lifts_composed(self, rng, monkeypatch):
        import pilosa_tpu.executor.stacked as stacked
        monkeypatch.setattr(
            stacked.StackedEngine, "_GROUPBY_KERNEL_MAX_COMBOS", 4)
        monkeypatch.setattr(stacked, "_REDUCE_MAX_SHARDS", 3)
        h = self._holder(rng, 1 << 12)
        self._cmp(h, "GroupBy(Rows(g), Rows(d), filter=Row(flt=0), "
                     "aggregate=Sum(field=v))", monkeypatch)


class TestGroupbyOnepass:
    """One-pass group-code histogram (ISSUE 1): the Pallas MXU kernel,
    the XLA scatter reference, the native host histogram, and the
    per-combo paths must all be bit-exact on disjoint-row data."""

    def _category_field(self, rng, n_rows, s_dim, width):
        """(rows (R, S, W) uint32, per-column assignment (S, width))
        with each column in at most one row — categorical data."""
        assign = rng.integers(-1, n_rows, size=(s_dim, width))
        rows = np.zeros((n_rows, s_dim, width // 32), np.uint32)
        for s in range(s_dim):
            for r in range(n_rows):
                rows[r, s] = bm.from_columns(
                    np.nonzero(assign[s] == r)[0], width)
        return rows, assign

    @pytest.mark.parametrize("signed,nf_rows,depth", [
        (True, (5, 3), 4),
        (False, (4,), 6),
        (True, (3, 2, 4), 3),
    ])
    def test_kernel_vs_xla_vs_naive(self, rng, signed, nf_rows, depth):
        """groupby_onehot (interpret) == groupby_codes_xla == numpy."""
        import jax.numpy as jnp
        s_dim, w = 3, 16
        width = w * 32
        fields = [self._category_field(rng, nr, s_dim, width)
                  for nr in nf_rows]
        lo = -(2 ** depth) + 1 if signed else 0
        vals = rng.integers(lo, 2 ** depth, size=(s_dim, width))
        ex = rng.integers(0, 2, size=(s_dim, width)).astype(bool)
        planes = np.stack([
            bsi.encode(np.nonzero(ex[s])[0], vals[s][ex[s]],
                       depth=depth, width=width) for s in range(s_dim)])
        bits = [max(nr - 1, 0).bit_length() for nr in nf_rows]
        n_codes = 1 << sum(bits)
        cp = np.concatenate(
            [np.asarray(bm.digit_planes(rows))
             for rows, _ in fields]).transpose(1, 0, 2) \
            if sum(bits) else np.zeros((s_dim, 0, w), np.uint32)
        valid = np.full((s_dim, w), 0xFFFFFFFF, np.uint32)
        for rows, _ in fields:
            u = rows[0].copy()
            for r in rows[1:]:
                u |= r
            valid &= u
        args = (jnp.asarray(cp), jnp.asarray(valid),
                jnp.asarray(planes), n_codes, signed)
        c_x, n_x, p_x, g_x = (np.asarray(v)
                              for v in kernels.groupby_codes_xla(*args))
        c_k, n_k, p_k, g_k = (np.asarray(v)
                              for v in kernels.groupby_onehot(*args))
        np.testing.assert_array_equal(c_x, c_k)
        np.testing.assert_array_equal(n_x, n_k)
        np.testing.assert_array_equal(p_x, p_k)
        np.testing.assert_array_equal(g_x, g_k)
        # naive per-combo ground truth over the dense code space
        import itertools
        shifts = np.cumsum([0] + bits[:-1])
        for combo in itertools.product(*[range(nr) for nr in nf_rows]):
            code = sum(ci << sh for ci, sh in zip(combo, shifts))
            sel = np.ones((s_dim, width), bool)
            for (rows, assign), ci in zip(fields, combo):
                sel &= assign == ci
            assert c_x[code] == sel.sum()
            sele = sel & ex
            assert n_x[code] == sele.sum()
            vv = vals[sele]
            mag = np.abs(vv)
            for p in range(depth):
                bit = (mag >> p) & 1
                assert p_x[code][p] == int(bit[vv >= 0].sum())
                assert g_x[code][p] == int(bit[vv < 0].sum())

    def _engine(self, rng, W, mutexes=True):
        from pilosa_tpu.models import FieldOptions, FieldType, Holder
        h = Holder(width=W)
        idx = h.create_index("i")
        gtype = FieldType.MUTEX if mutexes else FieldType.SET
        idx.create_field("g", FieldOptions(type=gtype))
        idx.create_field("d", FieldOptions(type=gtype))
        idx.create_field("flt")
        idx.create_field("v", FieldOptions(type=FieldType.INT,
                                           min=-50, max=50))
        idx.create_field("vu", FieldOptions(type=FieldType.INT,
                                            min=0, max=100))
        # step 3 is coprime to the row moduli, so g really has 5 rows
        # and d really has 4 (step 5 would collapse c % 5 to row 0)
        cols = list(range(0, 9 * W, 3))
        idx.field("g").import_bits([c % 5 for c in cols], cols)
        idx.field("d").import_bits([(c // 5) % 4 for c in cols], cols)
        idx.field("flt").import_bits([c % 2 for c in cols], cols)
        idx.field("v").import_values(
            cols, [int(v) for v in rng.integers(-50, 50,
                                                size=len(cols))])
        idx.field("vu").import_values(
            cols, [int(v) for v in rng.integers(0, 100,
                                                size=len(cols))])
        idx.mark_columns_exist(cols)
        return h

    QUERIES = [
        "GroupBy(Rows(g), Rows(d))",
        "GroupBy(Rows(g), Rows(d), aggregate=Sum(field=v))",
        "GroupBy(Rows(g), Rows(d), aggregate=Sum(field=vu))",
        "GroupBy(Rows(g), Rows(d), filter=Row(flt=1), "
        "aggregate=Sum(field=v))",
        "GroupBy(Rows(g), Rows(d), previous=[2, 1], "
        "aggregate=Sum(field=v))",
        "GroupBy(Rows(g), aggregate=Sum(field=v))",
    ]

    @staticmethod
    def _as_t(res):
        return [(tuple(g["row_id"] for g in r.group), r.count, r.agg,
                 r.agg_count) for r in res]

    def test_engine_three_way_bit_exact(self, rng, monkeypatch):
        """Acceptance property: one-pass == per-combo kernel == host
        loop through the REAL engine, across signed/unsigned BSI,
        filters, paging, counts-only."""
        from pilosa_tpu.executor import Executor
        h = self._engine(rng, 1 << 12)
        for q in self.QUERIES:
            monkeypatch.setenv("PILOSA_TPU_GROUPBY_ONEPASS", "1")
            one = Executor(h).execute("i", q)[0]
            monkeypatch.setenv("PILOSA_TPU_GROUPBY_ONEPASS", "0")
            monkeypatch.setenv("PILOSA_TPU_GROUPBY_KERNEL", "1")
            combo = Executor(h).execute("i", q)[0]
            monkeypatch.delenv("PILOSA_TPU_GROUPBY_KERNEL")
            ex_loop = Executor(h)
            ex_loop.use_stacked = False
            loop = ex_loop.execute("i", q)[0]
            monkeypatch.delenv("PILOSA_TPU_GROUPBY_ONEPASS")
            assert self._as_t(one) == self._as_t(loop), q
            assert self._as_t(combo) == self._as_t(loop), q

    def test_engine_onepass_mesh(self, rng, monkeypatch):
        """Multi-shard mesh: the shard_map/psum one-pass wrapper over
        a REAL 2x4 device mesh equals the host loop."""
        import jax

        from pilosa_tpu.executor import Executor
        from pilosa_tpu.parallel.mesh import make_mesh
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        h = self._engine(rng, 1 << 12)
        for q in self.QUERIES:
            ex_loop = Executor(h)
            ex_loop.use_stacked = False
            want = ex_loop.execute("i", q)[0]
            monkeypatch.setenv("PILOSA_TPU_GROUPBY_ONEPASS", "1")
            ex_mesh = Executor(h)
            ex_mesh.set_mesh(make_mesh(8, rows=2))
            got = ex_mesh.execute("i", q)[0]
            monkeypatch.delenv("PILOSA_TPU_GROUPBY_ONEPASS")
            assert self._as_t(got) == self._as_t(want), q

    def test_overlapping_rows_fall_back(self, rng, monkeypatch):
        """A column in TWO rows of one field belongs to two combos —
        inexpressible as a digit, so the disjointness gate must refuse
        one-pass even when forced, and results stay correct."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.obs.metrics import GROUPBY_ONEPASS
        W = 1 << 12
        h = self._engine(rng, W, mutexes=False)
        idx = h.index("i")
        # overlap: every 10th column joins g row 0 AND g row 1
        extra = list(range(0, 9 * W, 10))
        idx.field("g").import_bits([0] * len(extra), extra)
        idx.field("g").import_bits([1] * len(extra), extra)
        q = "GroupBy(Rows(g), Rows(d), aggregate=Sum(field=v))"
        before = GROUPBY_ONEPASS.value()
        monkeypatch.setenv("PILOSA_TPU_GROUPBY_ONEPASS", "1")
        got = Executor(h).execute("i", q)[0]
        monkeypatch.delenv("PILOSA_TPU_GROUPBY_ONEPASS")
        assert GROUPBY_ONEPASS.value() == before  # fell back
        ex_loop = Executor(h)
        ex_loop.use_stacked = False
        assert self._as_t(got) == self._as_t(ex_loop.execute("i", q)[0])

    def test_sparse_combo_selection_stays_per_combo(self, rng,
                                                    monkeypatch):
        """Cost model: a paged tail of 2 combos out of 20 is cheaper
        per-combo than a full-space histogram — one-pass must not
        claim it (but must still be forceable)."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.obs.metrics import GROUPBY_ONEPASS
        h = self._engine(rng, 1 << 12)
        q = "GroupBy(Rows(g), Rows(d), previous=[4, 1])"  # tail: 2
        before = GROUPBY_ONEPASS.value()
        got = Executor(h).execute("i", q)[0]
        assert GROUPBY_ONEPASS.value() == before
        monkeypatch.setenv("PILOSA_TPU_GROUPBY_ONEPASS", "1")
        forced = Executor(h).execute("i", q)[0]
        monkeypatch.delenv("PILOSA_TPU_GROUPBY_ONEPASS")
        assert GROUPBY_ONEPASS.value() == before + 1
        assert self._as_t(got) == self._as_t(forced)

    def test_numpy_fallback_histogram(self, rng, monkeypatch):
        """Host path without a toolchain (bincount fallback) matches
        the host loop."""
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.storage import native_ingest as ni
        monkeypatch.setattr(ni, "_lib", None)
        monkeypatch.setattr(ni, "_lib_failed", True)
        h = self._engine(rng, 1 << 12)
        q = "GroupBy(Rows(g), Rows(d), aggregate=Sum(field=v))"
        monkeypatch.setenv("PILOSA_TPU_GROUPBY_ONEPASS", "1")
        got = Executor(h).execute("i", q)[0]
        monkeypatch.delenv("PILOSA_TPU_GROUPBY_ONEPASS")
        ex_loop = Executor(h)
        ex_loop.use_stacked = False
        assert self._as_t(got) == self._as_t(ex_loop.execute("i", q)[0])

    def test_digit_planes_roundtrip(self, rng):
        """bitmap.digit_planes / code_from_planes invert each other on
        disjoint rows."""
        width = 1 << 9
        rows, assign = self._category_field(rng, 6, 2, width)
        dp = bm.digit_planes(rows)         # numpy in, numpy out
        assert isinstance(dp, np.ndarray) and dp.shape[0] == 3
        code = bm.code_from_planes_np(dp[:, 0])
        member = assign[0] >= 0
        np.testing.assert_array_equal(code[member], assign[0][member])
