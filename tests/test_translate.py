"""Key translation tests: stores, partitioning, ID allocation, and
executor integration (translate.go, idalloc.go, disco/snapshot.go)."""

import os

import pytest

from pilosa_tpu.executor import Executor
from pilosa_tpu.models import FieldOptions, FieldType, Holder
from pilosa_tpu.storage import (
    IDAllocator,
    PartitionedTranslator,
    TranslateStore,
    key_to_key_partition,
    next_partitioned_id,
    shard_to_shard_partition,
)

W = 1 << 12


class TestTranslateStore:
    def test_create_find_roundtrip(self):
        s = TranslateStore()
        ids = s.create_keys("a", "b", "c")
        assert len(set(ids.values())) == 3
        assert s.find_keys("a", "b") == {k: ids[k] for k in ("a", "b")}
        assert s.find_keys("missing") == {}  # not an error
        assert s.create_keys("a")["a"] == ids["a"]  # stable
        assert s.translate_ids(list(ids.values())) == ["a", "b", "c"]

    def test_sequential_ids_unpartitioned(self):
        s = TranslateStore()
        ids = s.create_keys("x", "y", "z")
        assert sorted(ids.values()) == [1, 2, 3]

    def test_persistence(self, tmp_path):
        p = str(tmp_path / "keys.jsonl")
        s = TranslateStore(p)
        ids = s.create_keys("k1", "k2")
        s.close()
        s2 = TranslateStore(p)
        assert s2.find_keys("k1", "k2") == ids
        assert s2.create_keys("k3")["k3"] > max(ids.values())

    def test_match(self):
        s = TranslateStore()
        s.create_keys("apple", "apricot", "banana")
        got = s.match(lambda k: k.startswith("ap"))
        assert got == sorted(s.find_keys("apple", "apricot").values())


class TestPartitioned:
    def test_partition_functions_deterministic(self):
        assert key_to_key_partition("i", "k") == key_to_key_partition("i", "k")
        assert 0 <= key_to_key_partition("i", "k") < 256
        assert 0 <= shard_to_shard_partition("i", 5) < 256

    def test_next_partitioned_id_lands_in_partition(self):
        for p in (0, 7, 255):
            id_ = next_partitioned_id("i", 0, p, shard_width=W)
            assert shard_to_shard_partition("i", id_ // W) == p

    def test_partitioned_translator(self, tmp_path):
        t = PartitionedTranslator("i", str(tmp_path), shard_width=W)
        keys = [f"user{n}" for n in range(50)]
        ids = t.create_keys(*keys)
        assert len(set(ids.values())) == 50
        # id -> key roundtrip through shard partition routing
        assert t.translate_ids([ids[k] for k in keys]) == keys
        # key lands in the partition its id's shard hashes to
        for k, id_ in ids.items():
            assert shard_to_shard_partition("i", id_ // W) == \
                key_to_key_partition("i", k)
        t.close()
        # reload from disk
        t2 = PartitionedTranslator("i", str(tmp_path), shard_width=W)
        assert t2.find_keys(*keys) == ids


class TestIDAllocator:
    def test_reserve_commit(self):
        a = IDAllocator()
        r1 = a.reserve("idx", b"s1", 10)
        assert list(r1) == list(range(0, 10))
        # same session re-reserves the same range (retry semantics)
        assert list(a.reserve("idx", b"s1", 10)) == list(r1)
        a.commit("idx", b"s1")
        r2 = a.reserve("idx", b"s2", 5)
        assert r2.start == 10

    def test_concurrent_sessions_disjoint(self):
        """Concurrent in-flight sessions on one key get DISJOINT
        ranges (per-clone ingesters, idk/ingest.go:302) and each
        session's retry still returns its own range."""
        a = IDAllocator()
        r1 = a.reserve("idx", b"s1", 10)
        r2 = a.reserve("idx", b"s2", 5)
        assert set(r1).isdisjoint(set(r2))
        assert list(a.reserve("idx", b"s1", 10)) == list(r1)
        assert list(a.reserve("idx", b"s2", 5)) == list(r2)
        a.commit("idx", b"s1")
        a.commit("idx", b"s2", count=2)  # tail 7..10 returns to pool
        assert a.reserve("idx", b"s3", 1).start == 12

    def test_rollback_returns_tail(self):
        a = IDAllocator()
        a.reserve("idx", b"s1", 10)
        a.rollback("idx", b"s1")  # newest reservation: tail returns
        assert a.reserve("idx", b"s2", 5).start == 0
        # rollback of a NON-newest reservation abandons its range
        a.reserve("idx", b"s3", 5)
        a.rollback("idx", b"s2")
        assert a.reserve("idx", b"s4", 1).start == 10

    def test_persistence(self, tmp_path):
        p = str(tmp_path / "ids.json")
        a = IDAllocator(p)
        a.reserve("idx", b"s", 7)
        a.commit("idx", b"s")
        a2 = IDAllocator(p)
        assert a2.reserve("idx", b"x", 1).start == 7


class TestKeyedQueries:
    @pytest.fixture
    def ex(self):
        h = Holder(width=W)
        return Executor(h), h

    def test_keyed_rows_and_columns(self, ex):
        ex, h = ex
        idx = h.create_index("i", keys=True)
        idx.create_field("f", FieldOptions(keys=True))
        ex.execute("i", 'Set("alice", f="admin")')
        ex.execute("i", 'Set("bob", f="admin")')
        ex.execute("i", 'Set("alice", f="eng")')
        res = ex.execute("i", 'Row(f="admin")')[0]
        assert sorted(res.keys) == ["alice", "bob"]
        assert ex.execute("i", 'Count(Row(f="admin"))')[0] == 2
        # unknown row key -> empty, not error (FindKeys semantics)
        assert ex.execute("i", 'Count(Row(f="nope"))')[0] == 0

    def test_keyed_rows_listing(self, ex):
        ex, h = ex
        idx = h.create_index("i", keys=True)
        idx.create_field("f", FieldOptions(keys=True))
        ex.execute("i", 'Set("a", f="x")Set("b", f="y")')
        assert sorted(ex.execute("i", "Rows(f)")[0]) == ["x", "y"]
        assert ex.execute("i", 'Rows(f, like="x%")')[0] == ["x"]

    def test_keyed_topn(self, ex):
        ex, h = ex
        idx = h.create_index("i", keys=True)
        idx.create_field("f", FieldOptions(keys=True))
        for c in "abc":
            ex.execute("i", f'Set("{c}", f="popular")')
        ex.execute("i", 'Set("a", f="rare")')
        pairs = ex.execute("i", "TopN(f)")[0]
        assert [(p.key, p.count) for p in pairs] == [
            ("popular", 3), ("rare", 1)]

    def test_keyed_groupby(self, ex):
        ex, h = ex
        idx = h.create_index("i", keys=True)
        idx.create_field("f", FieldOptions(keys=True))
        ex.execute("i", 'Set("u1", f="x")Set("u2", f="x")Set("u3", f="y")')
        got = ex.execute("i", "GroupBy(Rows(f))")[0]
        assert {g.group[0]["row_key"]: g.count for g in got} == \
            {"x": 2, "y": 1}

    def test_keyed_clear_and_includes(self, ex):
        ex, h = ex
        idx = h.create_index("i", keys=True)
        idx.create_field("f", FieldOptions(keys=True))
        ex.execute("i", 'Set("u1", f="x")')
        assert ex.execute(
            "i", 'IncludesColumn(Row(f="x"), column="u1")')[0] is True
        assert ex.execute(
            "i", 'IncludesColumn(Row(f="x"), column="zzz")')[0] is False
        assert ex.execute("i", 'Clear("u1", f="x")')[0] is True
        assert ex.execute("i", 'Count(Row(f="x"))')[0] == 0

    def test_unkeyed_rejects_string(self, ex):
        ex, h = ex
        from pilosa_tpu.executor.executor import ExecError
        idx = h.create_index("i")
        idx.create_field("f")
        with pytest.raises(ExecError):
            ex.execute("i", 'Set(1, f="key")')
        with pytest.raises(ExecError):
            ex.execute("i", 'Set("colkey", f=1)')

    def test_keyed_bsi_field(self, ex):
        ex, h = ex
        idx = h.create_index("i", keys=True)
        idx.create_field("age", FieldOptions(type=FieldType.INT))
        ex.execute("i", 'Set("alice", age=30)Set("bob", age=40)')
        res = ex.execute("i", "Row(age > 35)")[0]
        assert res.keys == ["bob"]
        assert ex.execute("i", "Sum(field=age)")[0].value == 70


def test_keyed_rows_column_filter():
    h = Holder(width=W)
    ex = Executor(h)
    idx = h.create_index("i", keys=True)
    idx.create_field("f", FieldOptions(keys=True))
    ex.execute("i", 'Set("c1", f="r1")Set("c2", f="r2")')
    assert ex.execute("i", 'Rows(f, column="c1")')[0] == ["r1"]
    assert ex.execute("i", 'Rows(f, column="missing")')[0] == []


def test_keyed_rows_previous_unknown_errors():
    from pilosa_tpu.executor.executor import ExecError
    h = Holder(width=W)
    ex = Executor(h)
    idx = h.create_index("i", keys=True)
    idx.create_field("f", FieldOptions(keys=True))
    ex.execute("i", 'Set("c", f="r")')
    with pytest.raises(ExecError):
        ex.execute("i", 'Rows(f, previous="zzz")')


def test_keyed_extract_translates():
    h = Holder(width=W)
    ex = Executor(h)
    idx = h.create_index("i", keys=True)
    idx.create_field("f", FieldOptions(keys=True))
    ex.execute("i", 'Set("u1", f="x")Set("u2", f="y")')
    got = ex.execute("i", "Extract(All(), Rows(f))")[0]
    by_key = {e["column_key"]: e["rows"][0] for e in got.columns}
    assert by_key == {"u1": ["x"], "u2": ["y"]}


def test_nested_distinct_keyed_field():
    h = Holder(width=W)
    ex = Executor(h)
    idx = h.create_index("i", keys=True)
    idx.create_field("f", FieldOptions(keys=True))
    ex.execute("i", 'Set("alice", f="admin")Set("bob", f="eng")')
    assert ex.execute("i", "Count(Distinct(field=f))")[0] == 2


def test_keyed_rejects_int_ids():
    from pilosa_tpu.executor.executor import ExecError
    h = Holder(width=W)
    ex = Executor(h)
    idx = h.create_index("i", keys=True)
    idx.create_field("f", FieldOptions(keys=True))
    with pytest.raises(ExecError):
        ex.execute("i", "Set(5, f=\"x\")")
    with pytest.raises(ExecError):
        ex.execute("i", "Set(\"c\", f=7)")


def test_like_matches_newline():
    h = Holder(width=W)
    ex = Executor(h)
    idx = h.create_index("i", keys=True)
    idx.create_field("f", FieldOptions(keys=True))
    ex.execute("i", 'Set("c", f="a\nb")')
    assert ex.execute("i", 'Rows(f, like="%")')[0] == ["a\nb"]


def test_idalloc_reservation_survives_restart(tmp_path):
    p = str(tmp_path / "ids.json")
    a = IDAllocator(p)
    r1 = a.reserve("idx", b"s1", 10)
    # process crash before commit: a retrying ingester with the same
    # session must get the same range
    a2 = IDAllocator(p)
    assert list(a2.reserve("idx", b"s1", 10)) == list(r1)
    a2.commit("idx", b"s1")
    assert a2.reserve("idx", b"s2", 1).start == 10


# -- snapshot-on-threshold compaction (storage v0 JSONL logs) ----------

class TestCompaction:
    def test_threshold_compacts_and_reloads(self, tmp_path):
        p = str(tmp_path / "keys.jsonl")
        s = TranslateStore(p, compact_threshold=50)
        s.create_keys(*[f"k{i}" for i in range(120)])
        assert os.path.exists(p + ".snap")
        # restart replays compact snapshot + bounded tail
        tail = sum(1 for ln in open(p) if ln.strip())
        assert tail < 50
        s2 = TranslateStore(p, compact_threshold=50)
        assert len(s2.keys()) == 120
        assert s2.max_id() == s.max_id()
        assert s2.find_keys("k77") == s.find_keys("k77")

    def test_torn_tail_restart_100k(self, tmp_path):
        """VERDICT weak #5: a 100k-key store whose log ends in a torn
        (crash-mid-append) line restarts cleanly — the torn record is
        dropped, every acked key survives, and id allocation
        continues exactly where it left off."""
        p = str(tmp_path / "keys.jsonl")
        s = TranslateStore(p, compact_threshold=60000)
        keys = [f"key-{i:06d}" for i in range(100000)]
        s.create_keys(*keys)
        mx = s.max_id()
        s.close()
        with open(p, "a") as f:
            f.write('{"id": 424242, "ke')  # torn mid-append
        s2 = TranslateStore(p, compact_threshold=60000)
        assert len(s2.keys()) == 100000
        assert s2.max_id() == mx
        assert s2.find_keys("key-054321")["key-054321"] == \
            s.find_keys("key-054321")["key-054321"]
        # the torn record must not poison later appends either
        nid = s2.create_keys("fresh")["fresh"]
        assert nid == mx + 1
        s2.close()
        s3 = TranslateStore(p)
        assert len(s3.keys()) == 100001

    def test_mid_file_corruption_still_raises(self, tmp_path):
        p = str(tmp_path / "keys.jsonl")
        s = TranslateStore(p, compact_threshold=0)  # never compact
        s.create_keys("a", "b")
        s.close()
        lines = open(p).read().splitlines()
        lines[0] = '{"broken'
        with open(p, "w") as f:
            f.write("\n".join(lines) + "\n")
        with pytest.raises(ValueError):
            TranslateStore(p)

    def test_restore_snapshot_refreshes_disk_state(self, tmp_path):
        p = str(tmp_path / "keys.jsonl")
        s = TranslateStore(p, compact_threshold=2)
        s.create_keys("x", "y", "z")  # compacts: .snap holds x,y,z
        s.restore_snapshot({"entries": [[1, "only"]]})
        s.close()
        s2 = TranslateStore(p)
        assert s2.keys() == ["only"]  # stale .snap must not resurrect
