"""SQL conformance tests, modeled on the reference's declarative
sql3/test/defs suites (defs_groupby.go, defs_join.go, ...)."""

import pytest
from decimal import Decimal

from pilosa_tpu.models import Holder
from pilosa_tpu.sql import SQLEngine, SQLError

W = 1 << 12


@pytest.fixture
def eng():
    e = SQLEngine(Holder(width=W))
    e.query("""
      CREATE TABLE orders (
        _id id, region string, status string, qty int,
        price decimal(2), tags stringset, paid bool
      )""")
    e.query("""
      INSERT INTO orders (_id, region, status, qty, price, tags, paid) VALUES
        (1, 'west', 'open',    5, '10.50', ('a','b'), true),
        (2, 'west', 'closed', 12,  '3.25', ('b'),     false),
        (3, 'east', 'open',    7, '99.99', ('a','c'), true),
        (4, 'east', 'open',    2,  '1.00', ('c'),     false),
        (5, 'north','closed', 12,  '0.75', ('a'),     true)""")
    return e


def rows(res):
    return res.rows


def test_show_tables_and_columns(eng):
    assert [r[1] for r in rows(eng.query_one("SHOW TABLES"))] == ["orders"]
    cols = {r[1]: r[2]
            for r in rows(eng.query_one("SHOW COLUMNS FROM orders"))}
    assert cols["qty"] == "int" and cols["region"] == "string"
    assert cols["tags"] == "stringset" and cols["price"] == "decimal"
    assert cols["_id"] == "id" and cols["paid"] == "bool"


def test_count_star(eng):
    assert rows(eng.query_one("SELECT COUNT(*) FROM orders")) == [(5,)]


def test_count_where(eng):
    q = "SELECT COUNT(*) FROM orders WHERE region = 'west'"
    assert rows(eng.query_one(q)) == [(2,)]
    q = "SELECT COUNT(*) FROM orders WHERE qty > 5 AND status = 'open'"
    assert rows(eng.query_one(q)) == [(1,)]
    q = "SELECT COUNT(*) FROM orders WHERE region = 'west' OR region = 'east'"
    assert rows(eng.query_one(q)) == [(4,)]
    q = "SELECT COUNT(*) FROM orders WHERE NOT status = 'open'"
    assert rows(eng.query_one(q)) == [(2,)]


def test_comparison_operators(eng):
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE qty >= 12")) == [(2,)]
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE qty != 12")) == [(3,)]
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE qty BETWEEN 5 AND 7")) == [(2,)]
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE price < 4")) == [(3,)]
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE paid = true")) == [(3,)]


def test_in_like(eng):
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE region IN ('west','north')")) \
        == [(3,)]
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE region NOT IN ('west')")) == [(3,)]
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE region LIKE 'w%'")) == [(2,)]


def test_id_filters(eng):
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE _id = 3")) == [(1,)]
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE _id IN (1, 2, 99)")) == [(2,)]


def test_set_membership(eng):
    # set columns match if ANY element equals
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE tags = 'a'")) == [(3,)]
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE tags = 'c'")) == [(2,)]


def test_aggregates(eng):
    assert rows(eng.query_one("SELECT SUM(qty) FROM orders")) == [(38,)]
    assert rows(eng.query_one("SELECT MIN(qty), MAX(qty) FROM orders")) == \
        [(2, 12)]
    # AVG returns a scale-4 decimal (defs_aggregate avgTests)
    r = rows(eng.query_one("SELECT AVG(qty) FROM orders"))[0][0]
    assert float(r) == pytest.approx(38 / 5)
    from decimal import Decimal
    assert isinstance(r, Decimal)
    assert rows(eng.query_one(
        "SELECT COUNT(DISTINCT region) FROM orders")) == [(3,)]
    assert rows(eng.query_one(
        "SELECT SUM(qty) FROM orders WHERE region = 'west'")) == [(17,)]
    assert rows(eng.query_one(
        "SELECT SUM(price) FROM orders"))[0][0] == Decimal("115.49")


def test_select_rows(eng):
    res = eng.query_one(
        "SELECT _id, qty FROM orders WHERE status = 'open' ORDER BY qty")
    assert res.schema == [("_id", "id"), ("qty", "int")]
    assert rows(res) == [(4, 2), (1, 5), (3, 7)]


def test_select_star(eng):
    res = eng.query_one("SELECT * FROM orders WHERE _id = 1")
    d = dict(zip([s[0] for s in res.schema], res.rows[0]))
    assert d["_id"] == 1 and d["qty"] == 5 and d["region"] == "west"
    assert sorted(d["tags"]) == ["a", "b"]
    assert d["price"] == Decimal("10.50") and d["paid"] is True


def test_order_limit_offset(eng):
    res = eng.query_one("SELECT _id FROM orders ORDER BY qty DESC LIMIT 2")
    assert rows(res) == [(2,), (5,)]
    res = eng.query_one(
        "SELECT _id FROM orders ORDER BY qty LIMIT 2 OFFSET 1")
    assert rows(res) == [(1,), (3,)]
    res = eng.query_one("SELECT _id FROM orders ORDER BY region")
    assert [r[0] for r in rows(res)] == [3, 4, 5, 1, 2]


def test_group_by(eng):
    res = eng.query_one("""
      SELECT region, COUNT(*), SUM(qty) FROM orders
      GROUP BY region ORDER BY region""")
    assert rows(res) == [("east", 2, 9), ("north", 1, 12), ("west", 2, 17)]


def test_group_by_having(eng):
    res = eng.query_one("""
      SELECT region, COUNT(*) FROM orders
      GROUP BY region HAVING COUNT(*) > 1 ORDER BY region""")
    assert rows(res) == [("east", 2), ("west", 2)]


def test_group_by_where(eng):
    res = eng.query_one("""
      SELECT status, COUNT(*) FROM orders WHERE qty > 4
      GROUP BY status ORDER BY status""")
    assert rows(res) == [("closed", 2), ("open", 2)]


def test_group_by_avg(eng):
    res = eng.query_one(
        "SELECT region, AVG(qty) FROM orders GROUP BY region ORDER BY region")
    d = dict(rows(res))
    assert d["west"] == Decimal("8.5")


def test_select_distinct(eng):
    res = eng.query_one("SELECT DISTINCT region FROM orders ORDER BY region")
    assert rows(res) == [("east",), ("north",), ("west",)]
    res = eng.query_one("SELECT DISTINCT qty FROM orders ORDER BY qty")
    assert rows(res) == [(2,), (5,), (7,), (12,)]


def test_is_null(eng):
    eng.query("INSERT INTO orders (_id, region) VALUES (9, 'south')")
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE qty IS NULL")) == [(1,)]
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE qty IS NOT NULL")) == [(5,)]
    assert rows(eng.query_one(
        "SELECT COUNT(*) FROM orders WHERE status IS NULL")) == [(1,)]


def test_delete(eng):
    eng.query("DELETE FROM orders WHERE region = 'west'")
    assert rows(eng.query_one("SELECT COUNT(*) FROM orders")) == [(3,)]


def test_insert_merge_and_replace(eng):
    eng.query("INSERT INTO orders (_id, tags) VALUES (1, ('z'))")
    res = eng.query_one("SELECT tags FROM orders WHERE _id = 1")
    assert sorted(res.rows[0][0]) == ["a", "b", "z"]  # INSERT merges sets
    eng.query("REPLACE INTO orders (_id, tags, qty) VALUES (1, ('q'), 3)")
    res = eng.query_one("SELECT tags, qty, region FROM orders WHERE _id = 1")
    assert res.rows[0][0] == ["q"]          # replaced
    assert res.rows[0][1] == 3
    assert res.rows[0][2] is None           # other columns cleared


def test_string_id_table():
    e = SQLEngine(Holder(width=W))
    e.query("CREATE TABLE users (_id string, role string, age int)")
    e.query("""INSERT INTO users (_id, role, age) VALUES
        ('alice', 'admin', 30), ('bob', 'eng', 40)""")
    res = e.query_one("SELECT _id, age FROM users WHERE role = 'admin'")
    assert res.rows == [("alice", 30)]
    assert res.schema[0] == ("_id", "string")


def test_errors(eng):
    with pytest.raises(SQLError):
        eng.query("SELECT nope FROM orders")
    with pytest.raises(SQLError):
        eng.query("SELECT * FROM missing")
    with pytest.raises(SQLError):
        eng.query("SELECT region, COUNT(*) FROM orders")  # no GROUP BY
    with pytest.raises(SQLError):
        eng.query("CREATE TABLE orders (_id id, x int)")  # exists
    with pytest.raises(SQLError):
        eng.query("SELECT garbage syntax here")


def test_multi_statement(eng):
    res = eng.query(
        "SELECT COUNT(*) FROM orders; SELECT SUM(qty) FROM orders")
    assert rows(res[0]) == [(5,)] and rows(res[1]) == [(38,)]


def test_percentile(eng):
    res = eng.query_one("SELECT PERCENTILE(qty, 50) FROM orders")
    vals = sorted([5, 12, 7, 2, 12])
    v = res.rows[0][0]
    assert sum(1 for x in vals if x < v) <= 2
    assert sum(1 for x in vals if x > v) <= 2


def test_create_if_exists_typo_rejected(eng):
    with pytest.raises(SQLError):
        eng.query("CREATE TABLE IF EXISTS t2 (_id id, x int)")
    eng.query("CREATE TABLE IF NOT EXISTS orders (_id id, x int)")  # no-op


def test_int_min_max_constraints(eng):
    eng.query("CREATE TABLE t2 (_id id, age int min 0 max 150)")
    idx = eng.holder.index("t2")
    assert idx.field("age").bit_depth == 8  # 150 needs 8 bits


def test_keyed_table_rejects_int_id():
    e = SQLEngine(Holder(width=W))
    e.query("CREATE TABLE u (_id string, r string)")
    with pytest.raises(SQLError):
        e.query("INSERT INTO u (_id, r) VALUES (7, 'x')")


def test_select_distinct_multi_column(eng):
    eng.query("""INSERT INTO orders (_id, region, status) VALUES
        (11, 'west', 'open'), (12, 'west', 'open')""")
    res = eng.query_one(
        "SELECT DISTINCT region, status FROM orders ORDER BY region")
    assert len(res.rows) == len(set(res.rows))


def test_having_without_group_by_rejected(eng):
    with pytest.raises(SQLError):
        eng.query("SELECT COUNT(*) FROM orders HAVING COUNT(*) > 100")


# -- regression tests: review findings on NULL/DISTINCT/DDL edge cases --


@pytest.fixture
def eng_nulls(eng):
    # row 9 exists (region set) but qty/price are NULL
    eng.query("INSERT INTO orders (_id, region) VALUES (9, 'west')")
    return eng


def test_grouped_avg_uses_nonnull_count(eng_nulls):
    got = dict(rows(eng_nulls.query_one(
        "SELECT region, AVG(qty) FROM orders GROUP BY region")))
    # west rows: qty 5, 12, NULL -> avg 8.5 (not 17/3)
    assert got["west"] == 8.5
    flat = rows(eng_nulls.query_one(
        "SELECT AVG(qty) FROM orders WHERE region = 'west'"))
    assert flat == [(8.5,)]


def test_order_by_bsi_keeps_null_rows(eng_nulls):
    got = rows(eng_nulls.query_one("SELECT _id FROM orders ORDER BY qty"))
    assert [r[0] for r in got] == [4, 1, 3, 2, 5, 9]  # NULL qty last
    got = rows(eng_nulls.query_one(
        "SELECT _id FROM orders ORDER BY qty DESC"))
    assert [r[0] for r in got][:2] == [2, 5] and got[-1][0] == 9
    # LIMIT spanning into the NULL tail
    got = rows(eng_nulls.query_one(
        "SELECT _id FROM orders ORDER BY qty LIMIT 6"))
    assert [r[0] for r in got] == [4, 1, 3, 2, 5, 9]


def test_distinct_multi_column_with_limit(eng):
    got = rows(eng.query_one(
        "SELECT DISTINCT status, paid FROM orders LIMIT 3"))
    assert len(got) == 3
    allr = rows(eng.query_one("SELECT DISTINCT status, paid FROM orders"))
    assert len(allr) == 4


def test_insert_int_id_into_string_column_rejected(eng):
    with pytest.raises(SQLError):
        eng.query("INSERT INTO orders (_id, region) VALUES (7, 42)")


def test_create_table_bad_option_leaves_no_table(eng):
    with pytest.raises(SQLError):
        eng.query("CREATE TABLE t2 (_id id, x idset timequantum 'BAD')")
    assert [r[1] for r in rows(eng.query_one("SHOW TABLES"))] == ["orders"]
    eng.query("CREATE TABLE t2 (_id id, x idset timequantum 'YMD')")
    assert "t2" in [r[1] for r in rows(eng.query_one("SHOW TABLES"))]


def test_create_table_duplicate_column_rejected(eng):
    with pytest.raises(SQLError):
        eng.query("CREATE TABLE t3 (_id id, x int, x int)")
    assert "t3" not in [r[1] for r in rows(eng.query_one("SHOW TABLES"))]


def test_grouped_sum_all_null_group(eng_nulls):
    # a SUM aggregate drops groups with no aggregate rows
    # (defs_groupby groupByTests_6; executor.go GroupBy aggregate
    # filtering)
    eng_nulls.query("INSERT INTO orders (_id, region) VALUES (10, 'south')")
    got = dict(rows(eng_nulls.query_one(
        "SELECT region, SUM(qty) FROM orders GROUP BY region")))
    assert "south" not in got
    assert got["west"] == 17


def test_inner_join_basic(engine=None):
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.sql.engine import SQLEngine

    eng = SQLEngine(Holder())
    eng.query("CREATE TABLE users (_id ID, name STRING, age INT MIN 0 MAX 120)")
    eng.query("CREATE TABLE orders (_id ID, user_id INT MIN 0 MAX 1000, "
              "amount INT MIN 0 MAX 10000)")
    eng.query("INSERT INTO users (_id, name, age) VALUES "
              "(1, 'alice', 30), (2, 'bob', 40), (3, 'carol', 50)")
    eng.query("INSERT INTO orders (_id, user_id, amount) VALUES "
              "(10, 1, 100), (11, 1, 150), (12, 2, 200), (13, 99, 5)")

    r = eng.query_one(
        "SELECT orders._id, users.name, orders.amount "
        "FROM orders INNER JOIN users ON orders.user_id = users._id "
        "ORDER BY amount DESC")
    assert [tuple(x) for x in r.rows] == [
        (12, "bob", 200), (11, "alice", 150), (10, "alice", 100)]
    assert [s[0] for s in r.schema] == ["orders._id", "users.name",
                                        "orders.amount"]

    # COUNT(*) over the join; order of ON sides is irrelevant
    r = eng.query_one(
        "SELECT COUNT(*) FROM orders JOIN users "
        "ON users._id = orders.user_id")
    assert r.rows == [(3,)]

    # WHERE may reference either side
    r = eng.query_one(
        "SELECT users.name FROM orders JOIN users "
        "ON orders.user_id = users._id "
        "WHERE users.age > 35 AND orders.amount >= 200")
    assert [tuple(x) for x in r.rows] == [("bob",)]

    # LIMIT applies post-join
    r = eng.query_one(
        "SELECT orders._id FROM orders JOIN users "
        "ON orders.user_id = users._id ORDER BY orders._id LIMIT 2")
    assert [x[0] for x in r.rows] == [10, 11]


def test_inner_join_errors():
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.sql.engine import SQLEngine
    from pilosa_tpu.sql.lexer import SQLError
    import pytest as _pytest

    eng = SQLEngine(Holder())
    eng.query("CREATE TABLE a (_id ID, x INT MIN 0 MAX 9)")
    eng.query("CREATE TABLE b (_id ID, y INT MIN 0 MAX 9)")
    with _pytest.raises(SQLError):
        eng.query("SELECT x FROM a JOIN b ON x = y")  # unqualified ON
    with _pytest.raises(SQLError):
        eng.query("SELECT x FROM a JOIN b ON a.x = a.x")  # one-sided
    with _pytest.raises(SQLError):
        eng.query("SELECT c.z FROM a JOIN b ON a.x = b.y")  # bad table


def test_copy_checks_src_read_permission(eng):
    """COPY must not bypass the source's read permission (r03 review:
    exfiltration into a writable destination)."""
    def deny_orders_read(table, perm):
        if table == "orders" and perm == "read":
            raise SQLError("denied")
    with pytest.raises(SQLError, match="denied"):
        eng.query("COPY orders TO mine", auth_check=deny_orders_read)
    # the denied copy must not leave a half-created table behind
    assert "mine" not in [r[1] for r in rows(eng.query_one("SHOW TABLES"))]


def test_const_select_limit_and_where(eng):
    assert rows(eng.query_one("SELECT 1 + 1 LIMIT 1")) == [(2,)]
    assert rows(eng.query_one("SELECT 1 LIMIT 0")) == []
    with pytest.raises(SQLError, match="projections only"):
        eng.query("SELECT 1 WHERE 1 = 1")


def test_const_select_udf_schema_type(eng):
    eng.query("CREATE FUNCTION dbl(@x int) RETURNS int AS (@x * 2)")
    res = eng.query_one("SELECT dbl(3)")
    assert res.schema == [("dbl", "int")]
    assert res.rows == [(6,)]


def test_hyphenated_identifiers_go_faithful(eng):
    """The reference scanner consumes '-' inside unquoted identifiers
    (sql3/parser/scanner.go isUnquotedIdent) — so `un-keyed` is a
    table name and UNSPACED subtraction like `qty-1` is a single
    (unknown) identifier there too.  Pin both behaviors."""
    eng.query("CREATE TABLE un-keyed (_id id, an_int int min 0 max 100)")
    eng.query("INSERT INTO un-keyed (_id, an_int) VALUES (1, 7)")
    assert rows(eng.query_one("SELECT an_int FROM un-keyed")) == [(7,)]
    # spaced subtraction is arithmetic...
    assert rows(eng.query_one(
        "SELECT an_int - 1 FROM un-keyed")) == [(6,)]
    # ...unspaced is one identifier, exactly like the reference
    with pytest.raises(SQLError, match="an_int-1"):
        eng.query("SELECT an_int-1 FROM un-keyed")


def test_delete_alias_and_qualifier_validation(eng):
    """DELETE FROM t alias parses; a WHERE qualifier naming an
    unknown table errors instead of silently resolving."""
    eng.query("CREATE TABLE deltest (_id id, qty int min 0 max 100)")
    eng.query("INSERT INTO deltest (_id, qty) VALUES (1, 1), (2, 9)")
    with pytest.raises(SQLError, match="unknown table"):
        eng.query("DELETE FROM deltest a1 WHERE bogus.qty = 9")
    eng.query("DELETE FROM deltest a1 WHERE a1.qty = 9")
    assert rows(eng.query_one("SELECT _id FROM deltest")) == [(1,)]


def test_where_like_uses_sql_scalar_semantics(eng):
    """WHERE LIKE follows the sql3 scalar regex (case-insensitive,
    '_' one-or-more; sql3/planner/expression.go:2991), matching the
    projection operator — the reference never pushes LIKE into PQL."""
    eng.query("CREATE TABLE liketest (_id id, s string)")
    eng.query("INSERT INTO liketest (_id, s) VALUES (1, 'foo'), (2, 'f')")
    assert rows(eng.query_one(
        "SELECT _id FROM liketest WHERE s LIKE '%f_'")) == [(1,)]
    assert rows(eng.query_one(
        "SELECT _id FROM liketest WHERE s LIKE 'FOO'")) == [(1,)]
    assert rows(eng.query_one(
        "SELECT s LIKE '%f_' FROM liketest WHERE _id = 1")) == [(True,)]


def test_ns_timestamp_predicate_boundaries(eng):
    """WHERE bounds on timeunit-'ns' columns compare at full
    nanosecond precision (Go time.Time is ns-exact; a µs-truncated
    parse would shift every boundary)."""
    eng.query("CREATE TABLE nsp (_id id, ts timestamp timeunit 'ns')")
    eng.query("INSERT INTO nsp (_id, ts) VALUES "
              "(1, '2012-11-01T22:08:41.100200300Z'), "
              "(2, '2012-11-01T22:08:41.100200301Z')")
    assert rows(eng.query_one(
        "select _id from nsp where ts > "
        "'2012-11-01T22:08:41.100200300Z'")) == [(2,)]
    assert rows(eng.query_one(
        "select _id from nsp where ts = "
        "'2012-11-01T22:08:41.100200301Z'")) == [(2,)]
    assert rows(eng.query_one(
        "select _id from nsp where ts between "
        "'2012-11-01T22:08:41.100200300Z' and "
        "'2012-11-01T22:08:41.100200300Z'")) == [(1,)]
