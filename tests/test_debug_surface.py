"""Debug-surface satellites (ISSUE 10): the admin-gating sweep over
EVERY /debug route (cluster + slo endpoints included), the
README<->registry metrics doc-sync gate, /debug/queries filter
params, and the logger's trace-id stamp."""

import io
import json
import re
import time

import pytest

from pilosa_tpu.obs import flight, metrics


def _req(port, method, path, body=None, headers=None):
    import http.client
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    data = json.dumps(body) if isinstance(body, (dict, list)) else body
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    c.request(method, path, body=data, headers=hdrs)
    r = c.getresponse()
    raw = r.read()
    c.close()
    try:
        return r.status, json.loads(raw)
    except json.JSONDecodeError:
        return r.status, raw.decode()


# ---------------------------------------------------------------------------
# admin-gating sweep: every /debug route honors _check_auth
# ---------------------------------------------------------------------------

# routes that need fast query params to avoid slow default collection
_PARAMS = {"/debug/profile": "?seconds=0.05&hz=20"}


def _debug_get_routes(server):
    """Every parameterless GET /debug/* route the server exposes —
    a future endpoint registers itself into this sweep for free."""
    return sorted(rt.pattern for rt in server._routes
                  if rt.method == "GET"
                  and rt.pattern.startswith("/debug")
                  and "{" not in rt.pattern)


@pytest.fixture(scope="module")
def auth_cluster():
    from pilosa_tpu.cluster import ClusterNode, InMemDisCo
    from pilosa_tpu.server.authn import Authenticator, encode_jwt
    from pilosa_tpu.server.authz import Authorizer

    secret = b"debug-sweep-secret"
    authn = Authenticator(secret)
    authz = Authorizer(user_groups={"readers": {"dq": "read"}},
                       admin_group="admins")
    atok = encode_jwt({"groups": ["admins"],
                       "exp": time.time() + 300}, secret)
    rtok = encode_jwt({"groups": ["readers"],
                       "exp": time.time() + 300}, secret)
    disco = InMemDisCo(lease_ttl=30)
    node = ClusterNode("node0", disco, replica_n=1,
                       heartbeat_interval=30,
                       auth=(authn, authz), auth_token=atok).open()
    yield node, atok, rtok
    node.close()


def test_debug_route_surface_includes_new_endpoints(auth_cluster):
    node, _atok, _rtok = auth_cluster
    routes = _debug_get_routes(node.server)
    for want in ("/debug/slo", "/debug/cluster/queries",
                 "/debug/cluster/metrics", "/debug/cluster/stats",
                 "/debug/queries", "/debug/trace", "/debug/faults",
                 "/debug/stats"):
        assert want in routes, routes


def test_every_debug_route_is_admin_gated(auth_cluster):
    """One sweep over the LIVE route table: no token -> 401, a
    read-only token -> 403, admin -> serves.  A future /debug
    endpoint that forgets gating fails here without a new test."""
    node, atok, rtok = auth_cluster
    port = node.server.port
    for pattern in _debug_get_routes(node.server):
        path = pattern + _PARAMS.get(pattern, "")
        st, _ = _req(port, "GET", path)
        assert st == 401, (pattern, st)
        st, _ = _req(port, "GET", path, headers={
            "Authorization": f"Bearer {rtok}"})
        assert st == 403, (pattern, st)
        st, _ = _req(port, "GET", path, headers={
            "Authorization": f"Bearer {atok}"})
        assert st == 200, (pattern, st)


# ---------------------------------------------------------------------------
# doc-sync: README metrics inventory <-> registry
# ---------------------------------------------------------------------------

_QUANTILE_SUFFIX = re.compile(r"_(p50|p95|p99|bucket|sum|count)$")


def _readme_metric_names() -> set[str]:
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "README.md"), encoding="utf-8") as f:
        text = f.read()
    names = set()
    for m in re.finditer(r"\bpilosa_[a-z0-9_]+", text):
        name = _QUANTILE_SUFFIX.sub("", m.group(0))
        if name == "pilosa_tpu":  # the package path, not a metric
            continue
        names.add(name)
    return names


def _registry_metric_names() -> set[str]:
    return {n for n in metrics.registry._metrics
            if n.startswith("pilosa_")}


def test_readme_metrics_inventory_in_sync():
    """Every registered metric appears in the README inventory and
    every pilosa_* metric the README mentions exists — the inventory
    has been hand-maintained across 9 PRs and WILL drift."""
    readme = _readme_metric_names()
    registry = _registry_metric_names()
    missing_from_readme = registry - readme
    assert not missing_from_readme, (
        f"metrics registered but absent from the README inventory: "
        f"{sorted(missing_from_readme)}")
    ghosts = readme - registry
    assert not ghosts, (
        f"README names metrics that no code registers: "
        f"{sorted(ghosts)}")


# ---------------------------------------------------------------------------
# doc-sync: README /debug endpoint inventory <-> live route table
# ---------------------------------------------------------------------------

_DEBUG_PATH = re.compile(r"(?<![\w/])/debug/[a-z][a-z0-9/-]*[a-z0-9]")


def _readme_debug_paths() -> set[str]:
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "README.md"), encoding="utf-8") as f:
        text = f.read()
    return {m.group(0) for m in _DEBUG_PATH.finditer(text)}


def test_readme_debug_endpoint_inventory_in_sync(auth_cluster):
    """BOTH ways (ISSUE 12): every /debug route the live table serves
    (cluster endpoints included) appears in the README, and every
    /debug path the README mentions is actually served — a new
    endpoint cannot ship undocumented, and docs cannot name ghosts.
    Gating rides the existing sweep: the same live route table feeds
    test_every_debug_route_is_admin_gated, so an endpoint cannot
    ship ungated either."""
    node, _atok, _rtok = auth_cluster
    routes = set(_debug_get_routes(node.server))
    readme = _readme_debug_paths()
    undocumented = routes - readme
    assert not undocumented, (
        f"/debug routes served but absent from the README: "
        f"{sorted(undocumented)}")
    ghosts = readme - routes
    assert not ghosts, (
        f"README names /debug paths no route serves: "
        f"{sorted(ghosts)}")


# ---------------------------------------------------------------------------
# federated filter passthrough (ISSUE 12 satellite)
# ---------------------------------------------------------------------------

def test_cluster_queries_federation_passes_filters(auth_cluster):
    """/debug/cluster/queries applies the per-node /debug/queries
    filters (route/tenant/since_ms) instead of ignoring them — the
    PR 9 merged endpoint dropped them on the floor."""
    node, atok, _rtok = auth_cluster
    port = node.server.port
    hdrs = {"Authorization": f"Bearer {atok}"}
    prev = (flight.recorder.enabled, flight.recorder._ring.maxlen)
    flight.recorder.configure(enabled=True, keep=256)
    try:
        _req(port, "POST", "/index/fq", {}, headers=hdrs)
        _req(port, "POST", "/index/fq/field/f", {}, headers=hdrs)
        _req(port, "POST", "/index/fq/query",
             {"query": "Set(1, f=1)"}, headers=hdrs)
        cut_ms = int(time.time() * 1000)
        time.sleep(0.01)
        for i in range(3):
            _req(port, "POST", "/index/fq/query",
                 {"query": f"Count(Row(f={i}))"},
                 headers={**hdrs, "X-Pilosa-Tenant": "acme"})

        def recs_of(d):
            return [r for ent in d["queries"]
                    for rs in ent["nodes"].values() for r in rs]

        st, d = _req(port, "GET",
                     "/debug/cluster/queries?tenant=acme&limit=100",
                     headers=hdrs)
        assert st == 200 and d["queries"]
        assert all(r["tenant"] == "acme" for r in recs_of(d))
        st, d = _req(port, "GET",
                     "/debug/cluster/queries?tenant=nobody&limit=100",
                     headers=hdrs)
        assert st == 200 and d["queries"] == []
        st, d = _req(
            port, "GET",
            f"/debug/cluster/queries?since_ms={cut_ms}&limit=100",
            headers=hdrs)
        assert st == 200 and d["queries"]
        assert all(r["start"] * 1000 >= cut_ms for r in recs_of(d))
        st, d = _req(port, "GET",
                     "/debug/cluster/queries?route=cached&limit=100",
                     headers=hdrs)
        assert st == 200
        assert all(r["route"] == "cached" for r in recs_of(d))
    finally:
        flight.recorder.configure(enabled=prev[0], keep=prev[1])


def test_cluster_stats_federation_and_filters(auth_cluster):
    """/debug/cluster/stats federates the per-node catalogs and
    passes the index/fingerprint/limit filters through — supported
    from day one (ISSUE 12)."""
    from pilosa_tpu.obs import stats

    node, atok, _rtok = auth_cluster
    port = node.server.port
    hdrs = {"Authorization": f"Bearer {atok}"}
    cat = stats.get()
    cat.note_ingest("csi", "f", rows=[0, 1], cols=[1, 2],
                    width=1 << 20)
    cat.note_ingest("other", "g", rows=[0], cols=[3], width=1 << 20)
    for _ in range(4):
        cat.note_flight({"fingerprint": "fedfp1", "route": "direct",
                         "duration_ms": 1.0, "phases": {},
                         "batch": 1, "bytes_moved": 0})
        cat.note_flight({"fingerprint": "fedfp2", "route": "direct",
                         "duration_ms": 2.0, "phases": {},
                         "batch": 1, "bytes_moved": 0})
    cat.fold()
    st, d = _req(port, "GET", "/debug/cluster/stats", headers=hdrs)
    assert st == 200
    assert d["nodes"] == ["node0"] and not d["partial"]
    assert "fedfp1" in d["aggregate"]["profiles"]
    assert d["aggregate"]["profiles"]["fedfp1"]["n"] >= 4
    # index filter narrows the data plane
    st, d = _req(port, "GET", "/debug/cluster/stats?index=csi",
                 headers=hdrs)
    local = d["per_node"]["node0"]
    assert "csi/f" in local["data"] and "other/g" not in local["data"]
    # fingerprint filter narrows the runtime plane
    st, d = _req(port, "GET",
                 "/debug/cluster/stats?fingerprint=fedfp2",
                 headers=hdrs)
    assert list(d["aggregate"]["profiles"]) == ["fedfp2"]
    # limit caps the profile listing
    st, d = _req(port, "GET", "/debug/cluster/stats?limit=1",
                 headers=hdrs)
    assert len(d["per_node"]["node0"]["runtime"]) == 1


# ---------------------------------------------------------------------------
# /debug/queries filter params
# ---------------------------------------------------------------------------

def test_debug_queries_filters_over_http():
    from pilosa_tpu.server.http import Server

    prev = (flight.recorder.enabled, flight.recorder._ring.maxlen)
    flight.recorder.configure(enabled=True, keep=256)
    srv = Server().start()
    try:
        flight.recorder.clear()
        _req(srv.port, "POST", "/index/df", {})
        _req(srv.port, "POST", "/index/df/field/f", {})
        _req(srv.port, "POST", "/index/df/query",
             {"query": "Set(1, f=1)"})
        cut_ms = int(time.time() * 1000)
        time.sleep(0.01)
        for i in range(3):
            _req(srv.port, "POST", "/index/df/query",
                 {"query": f"Count(Row(f={i}))"},
                 headers={"X-Pilosa-Tenant": "acme"})
        # limit
        st, d = _req(srv.port, "GET", "/debug/queries?limit=2")
        assert st == 200 and len(d["queries"]) == 2
        assert d["matched"] >= 3
        # route filter: the Set went through the write path, Counts
        # through the serving read path — no write record matches
        st, d = _req(srv.port, "GET",
                     "/debug/queries?route=cached&limit=100")
        assert st == 200
        assert all(r["route"] == "cached" for r in d["queries"])
        # tenant filter
        st, d = _req(srv.port, "GET",
                     "/debug/queries?tenant=acme&limit=100")
        assert st == 200 and d["queries"]
        assert all(r["tenant"] == "acme" for r in d["queries"])
        assert all(r["query"].startswith("Count")
                   for r in d["queries"])
        st, d = _req(srv.port, "GET",
                     "/debug/queries?tenant=nobody")
        assert st == 200 and d["queries"] == [] and d["matched"] == 0
        # since_ms: epoch-ms lower bound drops the earlier Set
        st, d = _req(srv.port, "GET",
                     f"/debug/queries?since_ms={cut_ms}&limit=100")
        assert st == 200 and d["queries"]
        assert all(r["start"] * 1000 >= cut_ms for r in d["queries"])
        assert not any(r["query"].startswith("Set")
                       for r in d["queries"])
        # combined: filters AND
        st, d = _req(srv.port, "GET",
                     "/debug/queries?tenant=acme&limit=1")
        assert len(d["queries"]) == 1 and d["matched"] >= 3
    finally:
        srv.close()
        flight.recorder.clear()
        flight.recorder.configure(enabled=prev[0], keep=prev[1])


# ---------------------------------------------------------------------------
# logger trace-id stamp
# ---------------------------------------------------------------------------

def test_logger_stamps_active_trace_id():
    from pilosa_tpu.obs.logger import Logger

    buf = io.StringIO()
    lg = Logger(stream=buf)
    lg.info("before any record")
    rec = flight.begin("i", "Count(All())")
    assert rec is not None
    lg.info("inside the record")
    flight.commit(rec, 0.001)
    lg.info("after commit")
    lines = buf.getvalue().splitlines()
    assert "trace=" not in lines[0]
    assert f"trace={rec['trace_id']}" in lines[1]
    # the stamp sits in the prefix, before the message
    assert lines[1].index("trace=") < lines[1].index("inside")
    assert "trace=" not in lines[2]


def test_logger_stamps_inherited_trace_id():
    from pilosa_tpu.obs.logger import Logger

    buf = io.StringIO()
    lg = Logger(stream=buf)
    prev = flight.inherit_trace("qremote7")
    try:
        lg.warn("remote leg log line")
    finally:
        flight.pop_inherit(prev)
    assert "trace=qremote7" in buf.getvalue()
