"""bench.py TPU-record carry-over (VERDICT r05 item 1): a CPU-fallback
run re-emits the committed BENCH_TPU_RECORD.json verbatim under
``last_tpu_record`` so TPU evidence survives tunnel outages."""

import json

import bench


def test_attach_tpu_record_present(tmp_path):
    rec = {"metric": "m", "platform": "tpu", "value": 1.23}
    p = tmp_path / "BENCH_TPU_RECORD.json"
    p.write_text(json.dumps(rec))
    out = bench.attach_tpu_record({"metric": "x"}, path=str(p),
                                  tunnel_down=True)
    assert out["last_tpu_record"] == rec
    assert "tunnel unreachable" in out["note"]
    assert "last_tpu_record is the committed raw record" in out["note"]


def test_attach_tpu_record_missing(tmp_path):
    out = bench.attach_tpu_record(
        {"metric": "x"}, path=str(tmp_path / "nope.json"))
    assert "last_tpu_record" not in out
    assert "no committed TPU record" in out["note"]


def test_attach_tpu_record_corrupt(tmp_path):
    p = tmp_path / "BENCH_TPU_RECORD.json"
    p.write_text("{truncated")
    out = bench.attach_tpu_record({"metric": "x"}, path=str(p))
    assert "JSONDecodeError" in out["last_tpu_record_error"]
