"""Roofline attribution tests (ISSUE 10): the bytes-touched x
device-time join per op family, peak handling, per-flight-record
shares, windowed bench snapshots, and the enable/disable seam the
overhead smoke gates."""

import pytest

from pilosa_tpu.executor.executor import Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.models.schema import FieldOptions, FieldType
from pilosa_tpu.obs import flight, metrics, roofline


@pytest.fixture(autouse=True)
def _seeded_peak():
    """Deterministic peak: tests must never trigger the measured
    probe (slow, backend-dependent)."""
    prev = roofline.peak_or_none()
    roofline.set_peak(10e9)  # 10 GB/s
    roofline.configure(enabled=True)
    yield
    roofline.reset_stats()
    if prev is not None:
        roofline.set_peak(prev)


def build_holder() -> Holder:
    h = Holder()
    idx = h.create_index("i", track_existence=True)
    idx.create_field("a")
    idx.create_field("b")
    idx.create_field("t")
    idx.create_field("age", FieldOptions(type=FieldType.INT,
                                         min=0, max=100))
    ex = Executor(h)
    for c in range(400):
        ex.execute("i", f"Set({c}, a={c % 3})")
        ex.execute("i", f"Set({c}, b={c % 5})")
        ex.execute("i", f"Set({c}, t={c % 7})")
        ex.execute("i", f"Set({c}, age={c % 50})")
    return h


@pytest.fixture(scope="module")
def holder():
    return build_holder()


def test_note_updates_gauges_and_snapshot():
    roofline.reset_stats()
    roofline.note("probe_op", 1 << 30, 0.5)  # 1 GiB in 0.5s ~ 2.1GB/s
    gbps = metrics.DEVICE_BW_GBPS.value(op="probe_op")
    frac = metrics.DEVICE_BW_FRACTION.value(op="probe_op")
    assert 2.0 < gbps < 2.2
    assert 0.20 < frac < 0.22
    snap = roofline.snapshot()
    assert snap["peak_gbps"] == 10.0
    ent = snap["ops"]["probe_op"]
    assert ent["bytes"] == 1 << 30 and ent["dispatches"] == 1
    assert "fraction" in ent


def test_window_diffs_two_snapshots():
    roofline.reset_stats()
    roofline.note("w_op", 1000, 0.001)
    s0 = roofline.snapshot()
    roofline.note("w_op", 5000, 0.002)
    roofline.note("w_new", 100, 0.001)
    w = roofline.window(s0, roofline.snapshot())
    assert w["ops"]["w_op"]["bytes"] == 5000
    assert w["ops"]["w_op"]["dispatches"] == 1
    assert w["ops"]["w_new"]["bytes"] == 100
    assert "fraction" in w["ops"]["w_op"]


def test_disabled_notes_nothing():
    roofline.reset_stats()
    roofline.configure(enabled=False)
    try:
        roofline.note("off_op", 1 << 20, 0.01)
        assert "off_op" not in roofline.snapshot()["ops"]
    finally:
        roofline.configure(enabled=True)


def test_peak_env_override(monkeypatch):
    monkeypatch.setattr(roofline, "_peak_bytes_per_s", None)
    monkeypatch.setenv("PILOSA_TPU_PEAK_GBPS", "123")
    assert roofline.ensure_peak() == 123e9
    assert metrics.DEVICE_PEAK_GBPS.value() == 123.0


@pytest.mark.parametrize("host_only", [False, True])
def test_populated_per_op_both_engines(holder, host_only, monkeypatch):
    """Acceptance: pilosa_device_bandwidth_fraction{op} populates for
    Count/TopN/GroupBy on the host and jit engines.  ONEPASS=1 routes
    the tiny test index through the one-pass GroupBy like the
    bench-scale data would route naturally; the filtered TopN forces
    the exact candidate scan (the unfiltered one answers from the
    ranked cache without touching a byte — correctly attributing
    nothing)."""
    monkeypatch.setenv("PILOSA_TPU_GROUPBY_ONEPASS", "1")
    roofline.reset_stats()
    ex = Executor(holder)
    ex.stacked.host_only = host_only
    for _ in range(2):  # 2nd round dispatches cached executables
        ex.execute("i", "Count(Row(a=1))")
        ex.execute("i", "TopN(t, Row(a=1), n=5)")
        ex.execute("i",
                   "GroupBy(Rows(a), Rows(b), aggregate=Sum(field=age))")
    snap = roofline.snapshot()
    for op in ("count", "topn", "groupby"):
        assert op in snap["ops"], (host_only, snap["ops"].keys())
        assert metrics.DEVICE_BW_FRACTION.value(op=op) > 0, op
        assert metrics.DEVICE_BW_GBPS.value(op=op) > 0, op


def test_flight_record_carries_roofline(holder):
    flight.recorder.configure(enabled=True)
    flight.recorder.clear()
    ex = Executor(holder)
    ex.execute("i", "Count(Row(b=2))")  # compile dispatch: no note
    ex.execute("i", "Count(Row(b=2))")  # cached dispatch: noted
    rec = flight.recorder.recent(5)[0]
    rl = rec.get("roofline")
    assert rl and "count" in rl, rec
    ent = rl["count"]
    assert ent["bytes"] > 0 and ent["ms"] > 0
    assert ent["gbps"] > 0 and 0 < ent["fraction"] <= 100


def test_compile_dispatches_never_note(holder):
    """A recompile's wall time is trace+XLA, not memory traffic — it
    must stay out of the bandwidth join."""
    roofline.reset_stats()
    ex = Executor(holder)
    # a fresh executor still reuses the process-global jit cache, so
    # force an unseen plan shape: first Xor over these operands
    ex.execute("i", "Count(Xor(Row(a=0), Row(b=4)))")
    snap1 = dict(roofline.snapshot()["ops"])
    ex.execute("i", "Count(Xor(Row(a=0), Row(b=4)))")
    snap2 = roofline.snapshot()["ops"]
    # the second (cached) dispatch noted; the first may only have
    # noted if the executable was already cached process-wide
    if "count" in snap1:
        assert snap2["count"]["dispatches"] >= snap1["count"]["dispatches"]
    else:
        assert "count" in snap2


def test_metrics_exposition_includes_roofline_series(holder):
    ex = Executor(holder)
    ex.execute("i", "Count(Row(a=1))")
    ex.execute("i", "Count(Row(a=1))")
    text = metrics.registry.render_text()
    assert "pilosa_device_bandwidth_fraction" in text
    assert "pilosa_device_bandwidth_gbps" in text
    assert "pilosa_device_peak_gbps" in text
