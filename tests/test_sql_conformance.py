"""Declarative SQL conformance runner (sql3/sql_test.go:34 analog):
executes every case in tests/sql_defs.py against a fresh engine."""

import pytest

from pilosa_tpu.models import Holder
from pilosa_tpu.sql import SQLEngine, SQLError

from tests.sql_defs import CASES, SETUP

W = 1 << 12


def fresh_engine() -> SQLEngine:
    e = SQLEngine(Holder(width=W))
    for stmt in SETUP:
        e.query(stmt)
    return e


def canon(rows):
    """Order-free multiset comparison key (lists inside rows sorted)."""
    def cell(v):
        return tuple(sorted(v)) if isinstance(v, list) else v
    return sorted(
        (tuple(cell(c) for c in r) for r in rows),
        key=repr)


@pytest.mark.parametrize(
    "name,sql,expected", CASES, ids=[c[0] for c in CASES])
def test_sql_conformance(name, sql, expected):
    eng = fresh_engine()
    if isinstance(expected, tuple) and expected and expected[0] == "error":
        with pytest.raises(SQLError) as exc:
            for res in eng.query(sql):
                pass
        assert expected[1].lower() in str(exc.value).lower(), exc.value
        return
    results = eng.query(sql)
    got = results[-1].rows
    if isinstance(expected, int):
        assert got == [(expected,)], got
    elif isinstance(expected, tuple) and expected[0] == "ordered":
        assert [tuple(r) for r in got] == expected[1], got
    else:
        assert canon(got) == canon(expected), (canon(got), canon(expected))


def test_case_count_meets_bar():
    """The suite must stay at or above the 100-case conformance bar."""
    assert len(CASES) >= 100, len(CASES)


def test_bulk_insert_from_file(tmp_path):
    """INPUT 'FILE' reads a real CSV from disk."""
    eng = fresh_engine()
    p = tmp_path / "orders.csv"
    p.write_text("40,mars,9\n41,mars,3\n")
    res = eng.query_one(
        f"BULK INSERT INTO orders (_id, region, qty) FROM '{p}' "
        "WITH FORMAT 'CSV' INPUT 'FILE'")
    assert res.rows == []  # like INSERT, no result set (reference)
    got = eng.query_one("SELECT _id FROM orders WHERE region = 'mars'")
    assert sorted(got.rows) == [(40,), (41,)]
